"""Disaggregated prefill/decode serving (ISSUE 20): role-split replicas
with live KV-page migration.

The load-bearing properties: BYTE IDENTITY (a request prefilled on
replica A and decoded on replica B emits exactly the greedy stream a
colocated engine emits — f32 and int8 kv_quant, scale pools bitwise,
sliding-window state included), ACCOUNTING (both replicas' page pools
exactly balanced after every handoff, including shared radix-tree prefix
pages and host-tier-resident pages on the source), and CONTAINMENT (a
faulted envelope or a killed prefill replica leaves every request wholly
arrived on the decode side or re-queued with a typed outcome — never
half a context). Plus the config grammar (``parse_roles``) and the
``router_bench --disagg --smoke`` verdict wiring.
"""

import dataclasses
import json
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from orion_tpu.config import get_config, parse_roles
from orion_tpu.infer import InferenceEngine, Router
from orion_tpu.models import init_params
from orion_tpu.runtime.fault import FaultInjector, FaultSpec

slow = pytest.mark.slow

INFER = [
    "inference.max_seq_len=128",
    "inference.page_size=16",
    "inference.num_pages=32",
    "inference.max_batch_size=4",
    "inference.prefill_chunk=16",
    "inference.max_new_tokens=24",
    # decode_window=2 keeps step boundaries fine-grained, so handoffs
    # land mid-stream instead of a whole request finishing in one step.
    "inference.decode_window=2",
]

PROMPT = [(i * 7) % 250 + 1 for i in range(20)]


def _setup(overrides=()):
    cfg = get_config("tiny-llama", list(INFER) + list(overrides))
    params = init_params(cfg.model, jax.random.key(0))
    return cfg, params


def _split_cfg(cfg, roles, replicas=3, **rkw):
    rcfg = dataclasses.replace(
        cfg.router, replicas=replicas, roles=roles, **rkw
    )
    return dataclasses.replace(cfg, router=rcfg)


def _handoff(src, dst, rid):
    """Full engine-level migration envelope src -> dst (what the router
    drives): export state + pages, import, atomic commit, teardown on
    the source. Returns (dst Request, the gathered blocks)."""
    state = src.export_migration_state(rid)
    live, blocks = src.export_migration_pages(rid)
    host_blocks = jax.device_get(blocks)
    token = dst.import_begin(state)
    dst.import_pages(token, live, host_blocks)
    req = dst.import_commit(token, src.export_migration_state(rid))
    assert req is not None, "commit deferred on an empty destination"
    src.finish_migration(rid)
    return req, host_blocks


def _drain(eng):
    done = {}
    while eng.has_work():
        for er in eng.step():
            done[er.rid] = er
    return done


# -- config grammar ----------------------------------------------------------


def test_parse_roles():
    assert parse_roles("prefill:1,decode:2") == {"prefill": 1, "decode": 2}
    assert parse_roles(" prefill:2 , decode:1 ") == {
        "prefill": 2, "decode": 1,
    }
    for bad in (
        "prefill",                 # no count
        "draft:1,decode:2",        # unknown role
        "prefill:x,decode:2",      # non-int count
        "prefill:0,decode:3",      # count < 1
        "prefill:1,prefill:2",     # repeated role
        "",                        # empty spec
    ):
        with pytest.raises(ValueError):
            parse_roles(bad)


def test_roles_config_validation():
    cfg, _ = _setup()
    # Counts must sum to the fleet size.
    with pytest.raises(ValueError, match="names 2 replicas"):
        _split_cfg(cfg, "prefill:1,decode:1", replicas=3)
    # Both roles must be present.
    with pytest.raises(ValueError, match="at least one"):
        _split_cfg(cfg, "prefill:3", replicas=3)
    # Per-chunk streaming is meaningless on a symmetric fleet.
    with pytest.raises(ValueError, match="requires router.roles"):
        dataclasses.replace(cfg.router, migrate_per_chunk=True)
    # The happy path constructs.
    _split_cfg(cfg, "prefill:1,decode:2", replicas=3)


# -- engine-level handoff ----------------------------------------------------


def test_engine_handoff_byte_identical():
    """Prefill on A, decode on B: the migrated stream is byte-identical
    to a colocated run, the source drains to empty, and both pools stay
    exactly accounted."""
    cfg, params = _setup()
    ref = InferenceEngine(cfg, params).generate([PROMPT], 24)[0]
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    rid = src.submit_request(PROMPT, 24).rid
    steps = 0
    while not src.migration_ready(rid):
        src.step()
        steps += 1
        assert steps < 50
    req, _ = _handoff(src, dst, rid)
    assert not src.has_work()
    src.assert_page_accounting()
    er = _drain(dst)[req.rid]
    assert er.outcome == "completed"
    assert list(er.generated) == ref
    dst.assert_page_accounting()


def test_engine_handoff_int8_scales_bitwise():
    """int8 kv_quant: the f32 k_scale/v_scale pools ride the copy
    envelope and land bitwise identical on the destination, and the
    migrated stream matches the colocated int8 run exactly."""
    cfg, params = _setup(["inference.kv_quant=int8"])
    ref = InferenceEngine(cfg, params).generate([PROMPT], 24)[0]
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    rid = src.submit_request(PROMPT, 24).rid
    while not src.migration_ready(rid):
        src.step()
    req, blocks = _handoff(src, dst, rid)
    assert {"k_scale", "v_scale"} <= set(blocks), sorted(blocks)
    # Re-gather the imported pages on the destination: every pool —
    # quantized KV and f32 scales — must be bitwise what was shipped.
    live = [j for j, p in enumerate(req.pages) if p is not None]
    back = jax.device_get(dst._gather_pages(
        dst.cache, jnp.asarray([req.pages[j] for j in live], jnp.int32)
    ))
    for name, sent in blocks.items():
        got = np.asarray(back[name][:len(live)])
        np.testing.assert_array_equal(got, np.asarray(sent)[:len(live)])
    er = _drain(dst)[req.rid]
    assert er.outcome == "completed"
    assert list(er.generated) == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_engine_handoff_sliding_window():
    """SWA: a request whose window already rolled pages dead migrates
    with its freed_until watermark — the destination never touches the
    rolled-dead logical pages and the stream stays byte-identical."""
    long_prompt = [(i * 5) % 250 + 1 for i in range(56)]
    cfg, params = _setup(["model.sliding_window=32"])
    ref = InferenceEngine(cfg, params).generate([long_prompt], 24)[0]
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    rid = src.submit_request(long_prompt, 24).rid
    while not src.migration_ready(rid):
        src.step()
    req, _ = _handoff(src, dst, rid)
    assert req.freed_until > 0, "window never rolled — test is vacuous"
    assert all(p is None for p in req.pages[:req.freed_until])
    er = _drain(dst)[req.rid]
    assert er.outcome == "completed"
    assert list(er.generated) == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_engine_handoff_mismatched_pools():
    """The copy envelope is pool-geometry independent: a destination
    with a DIFFERENT page pool (num_pages) imports the same blocks —
    logical page indices are preserved, physical placement is the
    destination allocator's business."""
    cfg, params = _setup()
    big = dataclasses.replace(
        cfg, inference=dataclasses.replace(cfg.inference, num_pages=64)
    )
    ref = InferenceEngine(cfg, params).generate([PROMPT], 24)[0]
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(big, params)
    rid = src.submit_request(PROMPT, 24).rid
    while not src.migration_ready(rid):
        src.step()
    req, _ = _handoff(src, dst, rid)
    er = _drain(dst)[req.rid]
    assert er.outcome == "completed"
    assert list(er.generated) == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_engine_handoff_page_size_mismatch_rejected():
    """Page size is the one geometry the blocks DO bake in: the
    destination must refuse the import up front, before any staging."""
    cfg, params = _setup()
    small = dataclasses.replace(
        cfg, inference=dataclasses.replace(cfg.inference, page_size=8)
    )
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(small, params)
    rid = src.submit_request(PROMPT, 24).rid
    while not src.migration_ready(rid):
        src.step()
    with pytest.raises(ValueError, match="page_size"):
        dst.import_begin(src.export_migration_state(rid))
    dst.assert_page_accounting()


def test_prefix_shared_page_migration_refcounts():
    """A request whose prompt rides radix-tree shared pages migrates by
    VALUE (the gather copies the shared page's bytes): the source tree's
    refcounts stay intact, the co-tenant still decodes byte-identically,
    and both pools account exactly."""
    warm = [(i * 3) % 250 + 1 for i in range(32)]   # 2 full pages
    p_a = warm + [61, 62, 63]
    p_b = warm + [71, 72, 73]
    cfg, params = _setup(["inference.prefix_cache=true"])
    ref = InferenceEngine(cfg, params).generate([p_a, p_b], 24)
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    rid_a = src.submit_request(p_a, 24).rid
    rid_b = src.submit_request(p_b, 24).rid
    while not (src.migration_ready(rid_a) and src.migration_ready(rid_b)):
        src.step()
    req_a, _ = _handoff(src, dst, rid_a)
    src.assert_page_accounting()     # tree refs: b still holds the warm path
    dst.assert_page_accounting()
    er_a = _drain(dst)[req_a.rid]
    er_b = _drain(src)[rid_b]
    assert list(er_a.generated) == ref[0]
    assert list(er_b.generated) == ref[1]
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_host_tier_restore_before_migrate():
    """Long-context source whose early pages were demoted to the host
    tier (inference.request_resident_pages): the export envelope pages
    them back in FIRST, so the gathered blocks are complete — and the
    handed-off stream is byte-identical to the colocated long-context
    run."""
    ov = [
        "inference.chunked_prefill=true",
        "inference.prefill_chunk_tokens=16",
        "inference.long_context=true",
        "inference.request_resident_pages=2",
        "inference.host_tier_bytes=262144",
        "inference.host_tier_min_tokens=0",
    ]
    long_prompt = [(i * 11) % 250 + 1 for i in range(80)]
    cfg, params = _setup(ov)
    ref = InferenceEngine(cfg, params).generate([long_prompt], 12)[0]
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    req_src = src.submit_request(long_prompt, 12)
    rid = req_src.rid
    # Step until the residency cap has demoted pages AND there are full
    # pages to stream — the per-chunk export must hit the restore path.
    steps = 0
    while not (
        req_src.host_pages
        and src.migration_in_prefill(rid)
        and src.migration_full_pages(rid) > 0
    ):
        src.step()
        steps += 1
        assert steps < 60, "residency cap never demoted — test is vacuous"
    state = src.export_migration_state(rid)
    token = dst.import_begin(state)
    live, blocks = src.export_migration_pages(
        rid, 0, src.migration_full_pages(rid)
    )
    assert live, "no full pages shipped"
    assert not req_src.host_pages, "export left host-resident pages behind"
    dst.import_pages(token, live, jax.device_get(blocks))
    shipped = max(live) + 1
    # Finish prefill on the source, ship the remainder, commit, tear down
    # — the same sequence the router's per-chunk driver runs.
    while not src.migration_ready(rid):
        src.step()
    live2, blocks2 = src.export_migration_pages(rid, shipped, None)
    if live2:
        dst.import_pages(token, live2, jax.device_get(blocks2))
    req = dst.import_commit(token, src.export_migration_state(rid))
    assert req is not None
    src.finish_migration(rid)
    er = _drain(dst)[req.rid]
    assert er.outcome == "completed"
    assert list(er.generated) == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_import_abort_frees_staged_pages():
    """A torn stream (source died before commit) unwinds the staging:
    import_abort frees every staged page and the destination pool is
    exactly where it started."""
    cfg, params = _setup()
    src = InferenceEngine(cfg, params)
    dst = InferenceEngine(cfg, params)
    rid = src.submit_request(PROMPT, 24).rid
    while not src.migration_ready(rid):
        src.step()
    free0 = dst.alloc.free_pages
    state = src.export_migration_state(rid)
    live, blocks = src.export_migration_pages(rid)
    token = dst.import_begin(state)
    dst.import_pages(token, live, jax.device_get(blocks))
    assert dst.alloc.free_pages < free0
    dst.import_abort(token)
    assert dst.alloc.free_pages == free0
    dst.assert_page_accounting()
    # Idempotent: a second abort of the same token is a no-op.
    dst.import_abort(token)


# -- router-driven migration -------------------------------------------------


def test_router_split_byte_identical():
    """roles="prefill:1,decode:2": every stream migrates exactly once,
    decode replicas never run prompt prefill, and the fleet output is
    byte-identical to a single-engine run."""
    cfg, params = _setup()
    prompts = [[(i * 7 + j) % 250 + 1 for i in range(20)] for j in range(3)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    r = Router(_split_cfg(cfg, "prefill:1,decode:2"), params)
    out = r.generate(prompts, 24)
    assert out == ref
    assert r.stats.migrations == 3
    assert r.stats.migrations_failed == 0
    for h in r.handles:
        h.engine.assert_page_accounting()
        if h.role == "decode":
            t = h.engine.reset_timing()
            assert t["prefill_s"] == 0.0 and t["prefill_chunks"] == 0
    r.close()


@slow
def test_router_split_int8_byte_identical():
    cfg, params = _setup(["inference.kv_quant=int8"])
    prompts = [[(i * 7 + j) % 250 + 1 for i in range(20)] for j in range(3)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    r = Router(_split_cfg(cfg, "prefill:1,decode:2"), params)
    assert r.generate(prompts, 24) == ref
    assert r.stats.migrations == 3
    for h in r.handles:
        h.engine.assert_page_accounting()
    r.close()


def test_router_per_chunk_streaming():
    """router.migrate_per_chunk with genuinely incremental prefill
    (chunked_prefill + a small per-step token budget): full pages below
    the watermark ship while the prompt is still prefilling, the commit
    still lands atomically, and the output is byte-identical."""
    ov = [
        "inference.chunked_prefill=true",
        "inference.prefill_chunk_tokens=16",
    ]
    cfg, params = _setup(ov)
    prompts = [[(i * 7 + j) % 250 + 1 for i in range(40)] for j in range(3)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    r = Router(
        _split_cfg(cfg, "prefill:1,decode:2", migrate_per_chunk=True),
        params,
    )
    assert r.generate(prompts, 24) == ref
    assert r.stats.migrations == 3
    for h in r.handles:
        h.engine.assert_page_accounting()
    r.close()


def test_migration_fault_containment():
    """Injected scatter faults across the first router steps: each
    failed envelope is counted and unwound (no torn pages anywhere);
    past the retry budget the request simply decodes colocated on its
    prefill replica — byte-identical either way."""
    cfg, params = _setup()
    prompts = [[(i * 7 + j) % 250 + 1 for i in range(40)] for j in range(4)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    inj = FaultInjector(
        [FaultSpec("migration", step=s, path="scatter") for s in range(3)]
    )
    r = Router(_split_cfg(cfg, "prefill:1,decode:2"), params,
               fault_injector=inj)
    assert r.generate(prompts, 24) == ref
    assert r.stats.migrations_failed >= 1
    for h in r.handles:
        h.engine.assert_page_accounting()
    r.close()


def test_kill_prefill_whole_or_requeued():
    """Kill a prefill replica mid-stream (chunked prefill keeps it
    genuinely mid-prompt): every request ends in exactly one typed
    outcome — wholly arrived on the decode side, completed colocated,
    re-queued with the retried tag, or typed error:migration — and
    every completed stream is byte-identical. Never half a context."""
    ov = [
        "inference.chunked_prefill=true",
        "inference.prefill_chunk_tokens=16",
    ]
    cfg, params = _setup(ov)
    prompts = [[(i * 7 + j) % 250 + 1 for i in range(40)] for j in range(4)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    inj = FaultInjector([FaultSpec("replica_kill", step=1, replica=0)])
    r = Router(
        _split_cfg(cfg, "prefill:2,decode:1", migrate_per_chunk=True),
        params, fault_injector=inj,
    )
    reqs = [r.submit_request(p, 24) for p in prompts]
    while r.has_work():
        r.step()
    assert all(rr.outcome for rr in reqs), [rr.outcome for rr in reqs]
    for rr, g in zip(reqs, ref):
        assert rr.outcome in ("completed", "shed", "error:migration")
        if rr.outcome == "completed":
            assert list(rr.generated) == g
    for h in r.handles:
        if not h.dead:
            h.engine.assert_page_accounting()
    r.close()


@slow
def test_router_split_int8_swa_per_chunk_composition():
    """The heavy composition: int8 scale pools + sliding window + per-
    chunk streaming through one handoff pipeline — byte-identical and
    exactly accounted."""
    ov = [
        "inference.kv_quant=int8",
        "model.sliding_window=32",
        "inference.chunked_prefill=true",
        "inference.prefill_chunk_tokens=16",
    ]
    cfg, params = _setup(ov)
    prompts = [[(i * 5 + j) % 250 + 1 for i in range(56)] for j in range(3)]
    ref = InferenceEngine(cfg, params).generate(prompts, 24)
    r = Router(
        _split_cfg(cfg, "prefill:1,decode:2", migrate_per_chunk=True),
        params,
    )
    assert r.generate(prompts, 24) == ref
    assert r.stats.migrations == 3
    for h in r.handles:
        h.engine.assert_page_accounting()
    r.close()


# ---------------------------------------------------------------------------
# tools/router_bench.py --disagg --smoke (the tier-1 acceptance wiring)
# ---------------------------------------------------------------------------


def test_disagg_bench_smoke():
    """tools/router_bench.py --disagg --smoke: colocated vs role-split
    at equal replica count under a prompt burst — role-split decode ITL
    p99 strictly better, every request migrated exactly once with
    measured latency percentiles, decode replicas never prefill, and the
    kill-a-prefill-worker chaos run resolves every request whole-or-
    requeued with zero silent drops."""
    root = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(root / "tools" / "router_bench.py"),
         "--disagg", "--smoke"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    verdict = lines[-1]
    assert verdict["verdict"] is True, lines
    assert verdict["chaos_kill_observed"] is True, lines
    assert verdict["chaos_migrations_requeued"] >= 0
    assert verdict["itl_p99_split_s"] < verdict["itl_p99_colocated_s"]
