"""Tiered prefix/KV cache (ISSUE 18): host-RAM second tier behind the
radix tree.

The load-bearing properties: BYTE IDENTITY (a page that round-trips
device -> host -> device is bitwise identical, scale pools included, and
the tier-off engine is byte-identical to a cache-on engine without the
tier), ACCOUNTING (both pools exactly balanced at every stage, including
after a mid-restore fault — no torn pages, no leaked slots, markers
unpromoted on unwind), and the BREAK-EVEN gate (a host match below
host_tier_min_tokens recomputes instead of restoring). Plus the fleet
half: the router's affinity probe sees host-tier matches, so a host-warm
replica beats a cold one.
"""

import json
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from orion_tpu.config import get_config
from orion_tpu.infer import InferenceEngine, Router
from orion_tpu.infer.kv_cache import (
    HostPagePool,
    PageAllocator,
    host_tier_break_even_tokens,
)
from orion_tpu.infer.prefix_cache import HostPage, PrefixCache
from orion_tpu.models import init_params
from orion_tpu.runtime.fault import FaultInjector, FaultSpec

slow = pytest.mark.slow

INFER = [
    "inference.max_seq_len=128",
    "inference.page_size=16",
    "inference.num_pages=32",
    "inference.max_batch_size=4",
    "inference.prefill_chunk=16",
    "inference.max_new_tokens=8",
]
# 16 host slots at tiny-llama's measured 8192 B/page; min_tokens=0 so
# every host match restores (the gate itself is tested separately).
TIER = [
    "inference.prefix_cache=true",
    "inference.host_tier_bytes=131072",
    "inference.host_tier_min_tokens=0",
]

SHARED = [(i * 7) % 250 + 1 for i in range(96)]          # 6 full pages


def _setup(overrides=(), tier=True):
    ov = list(INFER) + (list(TIER) if tier else [])
    cfg = get_config("tiny-llama", ov + list(overrides))
    params = init_params(cfg.model, jax.random.key(0))
    return cfg, params


def _snapshot_prefix(eng, tokens, n_pages):
    """Bitwise snapshot of the cached prefix path's KV (+ scale) pages."""
    pages, node = eng._pcache.match(tokens + [999], max_pages=n_pages)
    assert node is not None and len(pages) == n_pages
    assert all(isinstance(p, int) for p in pages)
    blocks = jax.device_get(
        eng._gather_pages(eng.cache, jnp.asarray(pages, dtype=jnp.int32))
    )
    eng._pcache.unlock(node)
    return {k: np.asarray(v) for k, v in blocks.items()}


# -- pure units --------------------------------------------------------------


def test_break_even_math():
    """t* = overhead / (1/prefill_tok_s - bytes_per_token/bw): known
    value, never-wins None, and the one-page floor."""
    # 1 MiB pages of 16 tokens over 8 GB/s vs 40k tok/s prefill: the
    # restore slope is ~8.2us/tok vs 25us/tok recompute -> 2ms overhead
    # amortises at 119 tokens.
    assert host_tier_break_even_tokens(1 << 20, 16, 8.0, 0.002, 40000.0) == 119
    # Restore slope >= recompute slope: the tier never pays.
    assert host_tier_break_even_tokens(1 << 20, 16, 0.01, 0.0, 40000.0) is None
    # Zero overhead still floors at one page (sub-page restores can't exist).
    assert host_tier_break_even_tokens(1024, 16, 8.0, 0.0, 40000.0) == 16


def test_host_pool_unit_mechanics():
    """alloc/retain/release/refcount, exhaustion, LRU eviction order,
    the evict-while-referenced refusal, and a store/load byte round-trip."""
    hp = HostPagePool(4, page_bytes=64)
    a, b, c = hp.alloc(3)
    assert hp.free_slots == 1
    assert [hp.refcount(x) for x in (a, b, c)] == [1, 1, 1]
    hp.retain(a)
    assert hp.refcount(a) == 2
    assert hp.release(a) is False and hp.refcount(a) == 1
    with pytest.raises(MemoryError):
        hp.alloc(2)                          # want 2, have 1
    # LRU order: touch a so b becomes coldest; b then c evict, a is
    # REFUSED while referenced (refcount 2 after re-retain).
    hp.touch(b); hp.touch(c); hp.touch(a)
    hp.retain(a)
    assert hp.evict_lru(3) == [b, c]         # a skipped: still referenced
    assert hp.free_slots == 3
    hp.release(a)
    assert hp.evict_lru(1) == [a]
    assert hp.free_slots == 4

    # store/load round-trip is bitwise, per-array, at the stored rows.
    hids = hp.alloc(2)
    rng = np.random.default_rng(0)
    blocks = {
        "k": rng.standard_normal((2, 3, 8)).astype(np.float32),
        "v": rng.integers(-128, 127, (2, 3, 8)).astype(np.int8),
    }
    hp.store(hids, blocks)
    out = hp.load(hids)
    for name in blocks:
        assert out[name].dtype == blocks[name].dtype
        assert out[name].tobytes() == blocks[name].tobytes()


def test_radix_demote_promote_unit():
    """Tree-level tier mechanics without an engine: demote flips trailing
    device entries to HostPage markers through ONE spill callback,
    promote_path flips them back, _discard and clear release host slots,
    and a locked path never demotes."""
    alloc = PageAllocator(64)
    hp = HostPagePool(8)
    spilled = []

    def spill(pages):
        hids = hp.alloc(len(pages))
        spilled.append(list(pages))
        return hids

    pc = PrefixCache(4, alloc, host_pool=hp, spill=spill)
    toks = list(range(12))                   # 3 pages of 4 tokens
    pages = alloc.alloc(3)
    pc.insert(toks, pages)
    alloc.free(pages)

    # Locked path: evict() finds nothing, demotes nothing.
    got, node = pc.match(toks + [99], max_pages=8)
    assert pc.evict(10) == 0 and not spilled
    pc.unlock(node)

    # Demote 2: ONE spill call carrying both victims (trailing entries
    # first), device refs released, markers in place, counters split.
    assert pc.demote(2) == 2
    assert len(spilled) == 1 and spilled[0] == [pages[2], pages[1]]
    assert (pc.total_pages, pc.host_pages) == (1, 2)
    assert all(alloc.refcount(p) == 0 for p in pages[1:])
    assert alloc.refcount(pages[0]) == 1
    assert hp.free_slots == 8 - 2

    # peek_tiered reports the split; the match surfaces the markers.
    matched, host, first_host = pc.peek_tiered(toks + [99], 8)
    assert (matched, host, first_host) == (3, 2, 1)
    got, node = pc.match(toks + [99], max_pages=8)
    assert got[0] == pages[0]
    assert [isinstance(p, HostPage) for p in got] == [False, True, True]

    # promote_path flips markers to fresh device pages and frees slots.
    fresh = alloc.alloc(2)
    pc.promote_path(node, {1: fresh[0], 2: fresh[1]})
    assert (pc.total_pages, pc.host_pages) == (3, 0)
    assert hp.free_slots == 8
    got2, node2 = pc.match(toks + [99], max_pages=8)
    assert got2 == [pages[0], fresh[0], fresh[1]]
    pc.unlock(node2)
    pc.unlock(node)
    # promote_path TRANSFERRED ownership of the fresh pages to the tree
    # (the engine's allocation ref becomes the tree's retain ref).
    assert all(alloc.refcount(p) == 1 for p in [pages[0]] + fresh)

    # clear() releases host slots too (re-demote first).
    assert pc.demote(3) == 3
    assert (pc.total_pages, pc.host_pages) == (0, 3)
    assert pc.clear() == 0                   # no DEVICE pages left to free
    assert pc.host_pages == 0 and hp.free_slots == 8


# -- engine round trip -------------------------------------------------------


def test_tier_off_by_default():
    """host_tier_bytes defaults to 0 (tier off, no host pool built); the
    tier requires the radix tree; offload without a tier is a no-op 0."""
    cfg, params = _setup(tier=False)
    assert cfg.inference.host_tier_bytes == 0
    eng = InferenceEngine(cfg, params)
    assert eng._host_pool is None
    assert eng.offload_prefix_cache() == 0
    with pytest.raises(ValueError, match="prefix_cache"):
        bad, _ = _setup(overrides=["inference.host_tier_bytes=131072"],
                        tier=False)
        InferenceEngine(bad, params)


def test_offload_restore_round_trip_byte_identical():
    """The tentpole pin: offload demotes the whole idle tree to host
    (counters + occupancy gauges move), a warm re-admission restores it,
    and the restored KV pages are BITWISE identical to the pre-offload
    snapshot — with both pools exactly accounted at every stage."""
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    cold = eng.generate([SHARED], 4)
    before = _snapshot_prefix(eng, SHARED, 6)
    eng.assert_page_accounting()
    eng.reset_timing()

    n = eng.offload_prefix_cache()
    assert n == 6
    assert (eng._pcache.total_pages, eng._pcache.host_pages) == (0, 6)
    t = eng.reset_timing()
    assert t["evicted_to_host"] == 6 and t["spill_s"] > 0
    m = eng._pool_metrics()
    assert m["host_pages"] == 6
    assert m["host_free_slots"] == m["host_capacity"] - 6
    assert 0 < m["host_occupancy"] <= 1
    eng.assert_page_accounting()

    # Warm re-admission: the host hit restores, then serves byte-identically.
    warm = eng.generate([SHARED], 4)
    assert warm == cold
    t = eng.reset_timing()
    assert t["host_hits"] == 1 and t["host_restored_pages"] == 6
    assert t["restore_s"] > 0
    assert t["prefix_hits"] == 1 and t["cached_tokens"] >= 95
    assert (eng._pcache.total_pages, eng._pcache.host_pages) == (6, 0)
    assert eng._host_pool.free_slots == eng._host_pool.capacity
    after = _snapshot_prefix(eng, SHARED, 6)
    assert set(before) == set(after)
    for name in before:
        assert after[name].dtype == before[name].dtype
        assert after[name].tobytes() == before[name].tobytes(), name
    eng.assert_page_accounting()


def test_tier_on_greedy_streams_byte_identical():
    """Tier-on serving (with an offload between rounds) never changes any
    request's tokens vs the tier-off cache-on AND cache-off engines."""
    cfg, params = _setup()
    cfg_pc, _ = _setup(tier=False, overrides=["inference.prefix_cache=true"])
    cfg_off, _ = _setup(tier=False)
    prompts = [SHARED[:48] + [7, 8, 9], SHARED[:48] + [200, 201], [5, 3, 9] * 6]
    ref = InferenceEngine(cfg_off, params).generate(prompts, 6)
    assert InferenceEngine(cfg_pc, params).generate(prompts, 6) == ref
    eng = InferenceEngine(cfg, params)
    assert eng.generate(prompts, 6) == ref           # cold round
    eng.offload_prefix_cache()
    assert eng.generate(prompts, 6) == ref           # host-warm round
    assert eng.reset_timing()["host_hits"] >= 1
    eng.assert_page_accounting()


def test_int8_round_trip_bitwise():
    """kv_quant=int8: the spill/restore copies carry the int8 KV pools AND
    the f32 scale pools; the round trip is bitwise on all of them."""
    cfg, params = _setup(overrides=["inference.kv_quant=int8"])
    eng = InferenceEngine(cfg, params)
    cold = eng.generate([SHARED], 4)
    before = _snapshot_prefix(eng, SHARED, 6)
    assert any(v.dtype == np.int8 for v in before.values())
    assert any("scale" in k for k in before), list(before)
    assert eng.offload_prefix_cache() == 6
    assert eng.generate([SHARED], 4) == cold
    after = _snapshot_prefix(eng, SHARED, 6)
    for name in before:
        assert after[name].dtype == before[name].dtype
        assert after[name].tobytes() == before[name].tobytes(), name
    eng.assert_page_accounting()


def test_restore_into_tight_pool_no_deadlock():
    """Restore when HBM is nearly full: the fresh-page allocation feeds
    through the normal evict-for-headroom path (demoting OTHER cold
    entries if needed) and completes — no deadlock, no accounting drift."""
    cfg, params = _setup(overrides=["inference.num_pages=16"])
    eng = InferenceEngine(cfg, params)
    cold = eng.generate([SHARED], 4)
    assert eng.offload_prefix_cache() == 6
    # Fill the tree with OTHER paths so free HBM pages are scarce when
    # the 6-page restore lands.
    filler = [[(i * 13 + j) % 250 + 1 for i in range(32)] for j in (1, 2)]
    fref = eng.generate(filler, 4)
    assert eng.generate([SHARED], 4) == cold
    t = eng.reset_timing()
    assert t["host_hits"] == 1 and t["host_restored_pages"] == 6
    eng.assert_page_accounting()
    assert eng.generate(filler, 4) == fref       # fillers still serve right
    eng.assert_page_accounting()


def test_break_even_gate_skips_small_match():
    """A host-resident match below host_tier_min_tokens recomputes: the
    skip counter moves, nothing restores, markers stay host-resident,
    and the served tokens are still byte-identical."""
    cfg, params = _setup(overrides=["inference.host_tier_min_tokens=999"])
    eng = InferenceEngine(cfg, params)
    cold = eng.generate([SHARED], 4)
    assert eng.offload_prefix_cache() == 6
    assert eng.generate([SHARED], 4) == cold
    t = eng.reset_timing()
    assert t["host_recompute_skips"] >= 1
    assert t["host_hits"] == 0 and t["host_restored_pages"] == 0
    assert eng._pcache.host_pages == 6           # markers untouched
    # The affinity probe applies the same gate: no phantom warm report.
    assert eng.prefix_match_tokens(SHARED + [1]) == 0
    eng.assert_page_accounting()


def test_mid_restore_fault_unwinds_both_tiers():
    """Chaos pin: an injected fault INSIDE the restore copy envelope
    fails the STEP with a typed outcome — fresh device pages freed, host
    refs dropped, markers unpromoted, both pools balanced — and the
    retry restores for real, byte-identically."""
    cfg, params = _setup()
    inj = FaultInjector()
    eng = InferenceEngine(cfg, params, fault_injector=inj)
    cold = eng.generate([SHARED], 4)
    assert eng.offload_prefix_cache() == 6
    free0 = eng.alloc.free_pages
    inj.specs.append(FaultSpec("restore", step=eng.step_no))
    eng.submit(SHARED, 4)
    eng.step()                                   # faulted admit step
    assert inj.fired == [("restore", eng.step_no - 1, None)]
    t = eng.reset_timing()
    assert t["failed_steps"] == 1 and t["dispatch_faults"] == 1
    # Full unwind: nothing promoted, nothing leaked, no torn pages.
    assert eng._pcache.host_pages == 6
    assert eng._pcache.total_pages == 0
    assert eng.alloc.free_pages == free0
    hp = eng._host_pool
    assert hp.free_slots == hp.capacity - 6
    eng.assert_page_accounting()
    # The retry (same queued request) restores and completes correctly.
    done = {}
    while eng.has_work():
        for r in eng.step():
            done[r.rid] = r
    assert [list(r.generated) for r in done.values()] == cold
    t = eng.reset_timing()
    assert t["host_hits"] == 1 and t["host_restored_pages"] == 6
    eng.assert_page_accounting()


# -- fleet warm-start --------------------------------------------------------


def test_router_prefers_host_warm_replica():
    """Two replicas, DISJOINT trees, replica 0's tree offloaded to host:
    the affinity probe still reports the (above-break-even) host match,
    so the shared-prefix request pins to replica 0 and serves it as a
    real host-tier hit — a host-warm replica beats a cold one."""
    cfg, params = _setup()
    warm_a = SHARED                              # 6 pages on replica 0
    warm_b = [(i * 11) % 250 + 1 for i in range(32)]
    r = Router(get_config("tiny-llama", INFER + TIER + [
        "router.replicas=2",
        "router.affinity_min_tokens=16",
        "router.retry_backoff_jitter=0",
    ]), params)
    pa = r.submit_request(warm_a + [40], 2)
    pb = r.submit_request(warm_b + [41], 2)
    while r.has_work():
        r.step()
    assert (pa.replica, pb.replica) == (0, 1)
    e0 = r.handles[0].engine
    assert e0.offload_prefix_cache() == 6
    assert e0._pcache.host_pages == 6
    # Probe sees the host-resident path; placement pins to replica 0.
    assert e0.prefix_match_tokens(warm_a + [1]) == 96
    r.reset_timing()
    q = r.submit_request(warm_a + [60, 61, 62], 4)
    assert q.replica == 0
    while r.has_work():
        r.step()
    assert r.reset_timing()["affinity_routes"] == 1
    t0 = e0.reset_timing()
    assert t0["host_hits"] == 1 and t0["host_restored_pages"] == 6
    for h in r.handles:
        h.engine.assert_page_accounting()
    r.close()


# -- compositions ------------------------------------------------------------


@slow   # heavy composition: int8 pools x chunked prefill x tier round trip
def test_kv_quant_chunked_long_prompt_composition():
    """kv_quant=int8 + chunked prefill + host tier on a near-capacity
    prompt: offload/restore mid-stream keeps serving correct (greedy
    stream equals the tier-off int8 engine's) and both pools accounted."""
    ov = ["inference.kv_quant=int8", "inference.max_seq_len=256",
          "inference.num_pages=24"]
    cfg, params = _setup(overrides=ov)
    cfg_off, _ = _setup(tier=False, overrides=ov)
    long_p = [(i * 3) % 250 + 1 for i in range(112)]     # 7 pages
    ref = InferenceEngine(cfg_off, params).generate([long_p], 8)
    eng = InferenceEngine(cfg, params)
    assert eng.generate([long_p], 8) == ref
    assert eng.offload_prefix_cache() > 0
    assert eng.generate([long_p], 8) == ref
    assert eng.reset_timing()["host_hits"] >= 1
    eng.assert_page_accounting()


# -- tools/prefix_cache_bench.py --capacity-sweep (tier-1 wiring) ------------


def test_capacity_sweep_bench_smoke():
    """The acceptance pin: the capacity sweep's host-tier TTFT (the
    admit-step compute span, prefill + restore) sits STRICTLY between
    device-warm and recompute at every pool size (the bench exits
    nonzero on inversion), real pages restored, and the measured
    d2h/h2d bandwidth constants present for PERF.md."""
    root = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [sys.executable, str(root / "tools" / "prefix_cache_bench.py"),
         "--capacity-sweep", "--smoke"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    verdict = lines[-1]
    assert verdict["verdict"] == "ok", lines
    for pool, ms in verdict["ttft_ms"].items():
        assert ms["warm"] < ms["host"] < ms["recompute"], verdict
    hosts = [d for d in lines[:-1] if d["phase"] == "host"]
    assert hosts and all(d["host_restored_pages"] > 0 for d in hosts)
    assert all("d2h_gbps" in d and "h2d_gbps" in d for d in hosts), hosts
