"""Lint-rule units (ISSUE 15 layer 2): every rule fires on a synthetic
violation, every suppression round-trips (allow -> suppressed -> removing
the code makes the allow itself a finding), and the repo itself sweeps
clean — the tier-1 CI hook for tools/lint.py."""

import os
import subprocess
import sys
from pathlib import Path

from orion_tpu.analysis import lint

ROOT = Path(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _unsuppressed(findings, rule=None):
    return [
        f for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# ---------------------------------------------------------------------------
# Rule units
# ---------------------------------------------------------------------------


def test_host_sync_rule_fires_and_scopes():
    src = (
        "import jax, numpy as np\n"
        "def _decode_all(self):\n"
        "    return np.asarray(jax.device_get(x))\n"
        "def helper_outside_scope(self):\n"
        "    return x.item()\n"
    )
    fs = lint.lint_source(src, "orion_tpu/infer/engine.py")
    hits = _unsuppressed(fs, "host-sync")
    # _decode_all is a dispatch body (both calls flagged); the helper is
    # outside the engine's scoped hot path.
    assert len(hits) == 2 and all(f.line == 3 for f in hits)

    # runner.py: EVERY function is traced code — the helper now counts.
    fs = lint.lint_source(src, "orion_tpu/infer/runner.py")
    assert len(_unsuppressed(fs, "host-sync")) == 3
    # Outside the dispatch modules the rule is silent.
    fs = lint.lint_source(src, "orion_tpu/train/trainer.py")
    assert _unsuppressed(fs, "host-sync") == []


def test_host_sync_nested_function_reported_once():
    """A call inside a helper nested in a dispatch body is ONE finding
    (the nested frame inherits the hot-path scope; the outer walk does
    not descend into it, so no double report)."""
    src = (
        "import jax\n"
        "def _decode_all(self):\n"
        "    def _inner():\n"
        "        return jax.device_get(x)\n"
        "    return _inner()\n"
    )
    fs = lint.lint_source(src, "orion_tpu/infer/engine.py")
    hits = _unsuppressed(fs, "host-sync")
    assert len(hits) == 1 and hits[0].line == 4


def test_host_sync_suppression_roundtrip():
    src = (
        "import jax\n"
        "def _decode_all(self):\n"
        "    return jax.device_get(x)  # orion: allow[host-sync] ONE fetch\n"
    )
    fs = lint.lint_source(src, "orion_tpu/infer/engine.py")
    assert _unsuppressed(fs) == []
    sup = [f for f in fs if f.suppressed]
    assert len(sup) == 1 and sup[0].reason == "ONE fetch"
    # Comment-above style also covers the next line.
    src2 = (
        "import jax\n"
        "def _decode_all(self):\n"
        "    # orion: allow[host-sync] ONE fetch\n"
        "    return jax.device_get(x)\n"
    )
    assert _unsuppressed(lint.lint_source(
        src2, "orion_tpu/infer/engine.py")) == []


def test_clock_rule_and_scope():
    src = "import time\nt = time.time()\n"
    assert len(_unsuppressed(
        lint.lint_source(src, "orion_tpu/obs/registry.py"), "clock")) == 1
    # tools/ may use wall clocks (bench stamps); the rule scopes to the
    # package.
    assert _unsuppressed(
        lint.lint_source(src, "tools/bench_thing.py"), "clock") == []
    ok = "import time\nt = time.perf_counter()\n"
    assert _unsuppressed(
        lint.lint_source(ok, "orion_tpu/obs/registry.py"), "clock") == []


def test_stats_timing_rule():
    bad = (
        "from dataclasses import dataclass\n"
        "@dataclass\n"
        "class FooStats:\n"
        "    n: int = 0\n"
    )
    fs = lint.lint_source(bad, "orion_tpu/metrics.py")
    assert len(_unsuppressed(fs, "stats-timing")) == 1
    good = bad + "    def as_timing(self):\n        return {}\n"
    # Re-parse: as_timing now inside the class body.
    good = (
        "from dataclasses import dataclass\n"
        "@dataclass\n"
        "class FooStats:\n"
        "    n: int = 0\n"
        "    def as_timing(self):\n"
        "        return {'n': self.n}\n"
    )
    assert _unsuppressed(
        lint.lint_source(good, "orion_tpu/metrics.py"), "stats-timing") == []
    # Non-dataclass *Stats (plain collector classes) are exempt.
    plain = "class BareStats:\n    pass\n"
    assert _unsuppressed(
        lint.lint_source(plain, "orion_tpu/metrics.py"), "stats-timing"
    ) == []


def test_config_validation_rule():
    src = (
        "from dataclasses import dataclass\n"
        "@dataclass(frozen=True)\n"
        "class FooConfig:\n"
        "    n: int = 0\n"
    )
    assert len(_unsuppressed(
        lint.lint_source(src, "orion_tpu/config.py"), "config-validation"
    )) == 1
    with_post = src + "    def __post_init__(self):\n        pass\n"
    assert _unsuppressed(
        lint.lint_source(with_post, "orion_tpu/config.py"),
        "config-validation") == []
    # Other modules' Config classes are out of scope.
    assert _unsuppressed(
        lint.lint_source(src, "orion_tpu/infer/engine.py"),
        "config-validation") == []


def test_fault_except_rule():
    bare = "try:\n    x = 1\nexcept:\n    pass\n"
    # Bare except is flagged everywhere.
    assert len(_unsuppressed(
        lint.lint_source(bare, "tools/somewhere.py"), "fault-except")) == 1
    broad = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert len(_unsuppressed(
        lint.lint_source(broad, "orion_tpu/infer/executor.py"),
        "fault-except")) == 1
    # Overbroad catches outside fault envelopes are allowed (metrics
    # providers etc. contain errors by design).
    assert _unsuppressed(
        lint.lint_source(broad, "orion_tpu/obs/registry.py"),
        "fault-except") == []
    typed = "try:\n    x = 1\nexcept ValueError:\n    pass\n"
    assert _unsuppressed(
        lint.lint_source(typed, "orion_tpu/infer/executor.py"),
        "fault-except") == []


def test_bad_allow_and_unused_allow():
    no_reason = (
        "import jax\n"
        "def _decode_all(self):\n"
        "    return jax.device_get(x)  # orion: allow[host-sync]\n"
    )
    fs = lint.lint_source(no_reason, "orion_tpu/infer/engine.py")
    rules = {f.rule for f in _unsuppressed(fs)}
    # The reasonless allow is itself a finding AND suppresses nothing.
    assert "bad-allow" in rules and "host-sync" in rules

    unknown = "x = 1  # orion: allow[warp-drive] because\n"
    fs = lint.lint_source(unknown, "orion_tpu/foo.py")
    assert [f.rule for f in _unsuppressed(fs)] == ["bad-allow"]

    stale = "x = 1  # orion: allow[clock] leftover reason\n"
    fs = lint.lint_source(stale, "orion_tpu/foo.py")
    assert [f.rule for f in _unsuppressed(fs)] == ["unused-allow"]


def test_unparseable_file_is_a_parse_error_finding(tmp_path):
    fs = lint.lint_source("def broken(:\n", "orion_tpu/x.py")
    assert [f.rule for f in fs] == ["parse-error"]


def test_allow_inside_string_literal_is_inert():
    """Allow-shaped text inside a STRING (a docstring quoting the
    syntax) must neither suppress a neighboring finding nor register as
    an unused allow — only real comment tokens count."""
    src = (
        "import time\n"
        'DOC = "example: # orion: allow[clock] sample reason"\n'
        "t = time.time()\n"
    )
    fs = lint.lint_source(src, "orion_tpu/obs/foo.py")
    assert [f.rule for f in _unsuppressed(fs)] == ["clock"]
    assert not any(f.suppressed for f in fs)


# ---------------------------------------------------------------------------
# The repo itself
# ---------------------------------------------------------------------------


def test_repo_sweeps_clean():
    """The acceptance pin: zero unsuppressed findings across orion_tpu/,
    tools/, and the entry scripts — every violation the first full sweep
    surfaced was fixed or justify-suppressed (ISSUE 15)."""
    findings = lint.lint_paths(ROOT)
    unsup = _unsuppressed(findings)
    assert unsup == [], "\n" + "\n".join(str(f) for f in unsup)
    # The suppressed set is the justified inventory: every one carries a
    # reason (bad-allow would have fired otherwise).
    assert all(f.reason for f in findings if f.suppressed)


def test_lint_cli_exit_codes(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "lint.py")],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    # --diff scopes to changed files (vs HEAD there may be none — the
    # command must still succeed and report its scope).
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "lint.py"), "--diff"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "scope:" in proc.stdout
