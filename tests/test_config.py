"""Unit tests for the config system (SURVEY.md §6 config/flag system)."""

import pytest

from orion_tpu.config import (
    Config,
    ParallelConfig,
    apply_overrides,
    get_config,
    list_presets,
)


def test_presets_cover_baseline_workloads():
    # The five BASELINE.json workloads must all have presets.
    names = list_presets()
    for required in (
        "gpt2-125m",
        "llama3-8b-dp",
        "llama3-70b-fsdp",
        "mixtral-8x7b-ep",
        "llama3-8b-infer",
    ):
        assert required in names


def test_overrides_typed():
    cfg = get_config("tiny", ["model.n_layers=3", "data.batch_size=2",
                              "optimizer.learning_rate=1e-3",
                              "model.tie_embeddings=false"])
    assert cfg.model.n_layers == 3
    assert cfg.data.batch_size == 2
    assert cfg.optimizer.learning_rate == pytest.approx(1e-3)
    assert cfg.model.tie_embeddings is False


def test_overrides_optional_and_tuple_types():
    # Regression: `from __future__ import annotations` stringifies field types;
    # overrides must still resolve Optional[int] / Tuple[...] correctly.
    cfg = apply_overrides(Config(), [
        "model.head_dim=64",
        "optimizer.decay_steps=2000",
        "train.profile_steps=10,20",
        "parallel.dcn_axes=dp",
        "model.head_dim=none",
    ])
    assert cfg.model.head_dim is None
    assert cfg.optimizer.decay_steps == 2000
    assert cfg.train.profile_steps == (10, 20)
    assert cfg.parallel.dcn_axes == ("dp",)


def test_override_unknown_key_raises():
    with pytest.raises(ValueError, match="unknown config key"):
        apply_overrides(Config(), ["model.not_a_field=1"])


def test_parallel_num_devices():
    p = ParallelConfig(dp=2, fsdp=2, tp=2)
    assert p.num_devices == 8


def test_param_count_sane():
    gpt2 = get_config("gpt2-125m").model
    # GPT-2 125M: ~124M params (with the padded 50304 vocab).
    n = gpt2.num_params()
    assert 100e6 < n < 180e6

    llama = get_config("llama3-8b-dp").model
    n = llama.num_params()
    assert 7e9 < n < 9e9

    llama70 = get_config("llama3-70b-fsdp").model
    assert 65e9 < llama70.num_params() < 75e9


def test_moe_flops_use_active_experts_only():
    mix = get_config("mixtral-8x7b-ep").model
    dense_equiv = mix.flops_per_token()
    # Active params ~13B of 47B total: flops must be well under total-param flops.
    assert dense_equiv < 6 * mix.num_params()


def test_config_json_roundtrip():
    cfg = get_config("tiny")
    s = cfg.to_json()
    assert '"n_layers": 2' in s


def test_tuple_override_forms():
    """Tuple overrides accept python-repr, bare, and json forms; elements
    are typed (the '(5,7)' form previously parsed to ('(5', '7)') strings,
    silently disabling train.profile_steps)."""
    from orion_tpu.config import get_config

    for ov, want in [
        ("train.profile_steps=(5,7)", (5, 7)),
        ("train.profile_steps=5,7", (5, 7)),
        ("train.profile_steps=[5,7]", (5, 7)),
        ("train.profile_steps=none", None),
    ]:
        assert get_config("tiny", [ov]).train.profile_steps == want, ov
    for ov, want in [
        ('parallel.dcn_axes=("dp",)', ("dp",)),
        ("parallel.dcn_axes=dp", ("dp",)),
        ("parallel.dcn_axes=dp,fsdp", ("dp", "fsdp")),
    ]:
        assert get_config("tiny", [ov]).parallel.dcn_axes == want, ov


def test_leaf_configs_validate_and_overrides_batch_per_section():
    """ISSUE 15: every leaf *Config validates in __post_init__, and
    same-section overrides apply as ONE replace — cross-field checks
    (memmap-requires-path) hold in either flag order."""
    import pytest

    from orion_tpu.config import (
        DataConfig, OptimizerConfig, RuntimeConfig, get_config,
    )

    # Cross-field check is order-independent under the override parser.
    for order in (
        ["data.source=memmap", "data.path=/tmp/x.bin"],
        ["data.path=/tmp/x.bin", "data.source=memmap"],
    ):
        assert get_config("tiny", order).data.source == "memmap"
    with pytest.raises(ValueError, match="requires data.path"):
        get_config("tiny", ["data.source=memmap"])

    with pytest.raises(ValueError, match="learning_rate"):
        OptimizerConfig(learning_rate=0.0)
    with pytest.raises(ValueError, match="schedule"):
        OptimizerConfig(schedule="sawtooth")
    with pytest.raises(ValueError, match="b2"):
        OptimizerConfig(b2=1.0)
    with pytest.raises(ValueError, match="batch_size"):
        DataConfig(batch_size=0)
    with pytest.raises(ValueError, match="coordinator_address"):
        RuntimeConfig(num_processes=2)
    with pytest.raises(ValueError, match="process_id"):
        RuntimeConfig(num_processes=2, process_id=5,
                      coordinator_address="h:1234")
    with pytest.raises(ValueError, match="platform"):
        RuntimeConfig(platform="abacus")
    with pytest.raises(ValueError, match="moment_dtype"):
        OptimizerConfig(moment_dtype="flaot32")
