"""Pipeline row-state validation (ADVICE r5) — fast, execution-free
checks that stay in tier-1 while the pipeline-execution tests (slow tier
on jax-0.4.37 boxes) carry the schedule equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import make_mesh


def test_pipeline_row_state_broadcast_lifted():
    """A [1, S] broadcast row-state leaf (explicitly supported by the
    non-pp block_fn) is lifted to [B, S] before microbatch slicing instead
    of dying in an opaque reshape (ADVICE r5)."""
    from orion_tpu.parallel.pipeline import validate_row_state

    rs = validate_row_state(
        {"positions": jnp.arange(8, dtype=jnp.int32)[None],   # [1, 8]
         "segment_ids": jnp.ones((4, 8), jnp.int32)},
        batch=4, num_microbatches=2,
    )
    assert rs["positions"].shape == (4, 8)
    assert rs["segment_ids"].shape == (4, 8)
    np.testing.assert_array_equal(
        np.asarray(rs["positions"]), np.tile(np.arange(8), (4, 1))
    )
    assert validate_row_state(None, batch=4, num_microbatches=2) is None


def test_pipeline_row_state_bad_leading_dim_raises(cpu_devices):
    """A row-state leaf whose leading dim is neither B nor 1 must raise a
    descriptive ValueError up front, from the real pipeline entry point
    (ADVICE r5: it previously surfaced as an opaque reshape error)."""
    from orion_tpu.parallel.pipeline import pipeline_forward

    mesh = make_mesh(cpu_devices, pp=2, dp=4)
    x = jnp.zeros((4, 8, 16))
    blocks = {"w": jnp.zeros((4, 1, 1))}

    def fn(c, bp, rs):
        return c + bp["w"], jnp.zeros(())

    with pytest.raises(ValueError, match="row_state"):
        pipeline_forward(
            x, blocks, fn, mesh, num_microbatches=2,
            row_state={"positions": jnp.zeros((3, 8), jnp.int32)},
        )
