"""Weight-only int8 serving quantization (models/quantize.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from orion_tpu.config import get_config
from orion_tpu.infer import InferenceEngine
from orion_tpu.models import forward, init_params
from orion_tpu.models.quantize import (
    load_weight,
    quantize_params,
    quantize_weight,
)

# Revived on jax-0.4.37 boxes by the round-6 compat shims (previously a
# collection error), but too heavy for the tier-1 CPU budget — the serving
# stack (test_infer / test_prefix_cache) owns that budget this round. Runs
# in the full tier (no `-m "not slow"`).
pytestmark = pytest.mark.slow


def test_quantize_weight_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (64, 32)) * jnp.exp(
        jax.random.normal(jax.random.key(1), (1, 32))  # varied channel scales
    )
    deq = load_weight(quantize_weight(w), jnp.float32)
    err = jnp.abs(deq - w)
    bound = jnp.max(jnp.abs(w), axis=0) / 127.0 * 0.5 + 1e-6
    assert (err <= bound[None, :] * 1.001).all()


def test_quantize_weight_stacked_per_layer_scales():
    w = jnp.stack([jnp.ones((8, 4)), 100.0 * jnp.ones((8, 4))])
    qw = quantize_weight(w)
    assert qw["q"].shape == (2, 8, 4) and qw["s"].shape == (2, 4)
    np.testing.assert_allclose(
        np.asarray(load_weight(qw, jnp.float32)), np.asarray(w), rtol=1e-2
    )


def test_quantized_forward_close_to_fp():
    cfg = get_config("tiny-llama").model
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    ref, _ = forward(params, tokens, cfg)
    qparams = quantize_params(params, cfg)
    # The eligible matmul weights actually became int8.
    assert qparams["blocks"]["attn"]["wq"]["q"].dtype == jnp.int8
    out, _ = forward(qparams, tokens, cfg)
    rel = float(
        jnp.linalg.norm(out - ref) / (jnp.linalg.norm(ref) + 1e-9)
    )
    assert rel < 0.05, rel


def test_quantized_engine_matches_quantized_forward():
    """Serving-path exactness: the engine with int8 weights reproduces
    greedy generation from the SAME quantized model's training forward
    (quantization changes the model; serving must not add divergence)."""
    cfg = get_config("tiny-llama", [
        "model.weight_quant=int8",
        "inference.max_seq_len=128", "inference.page_size=16",
        "inference.num_pages=32", "inference.max_batch_size=4",
        "inference.prefill_chunk=16",
    ])
    params = init_params(cfg.model, jax.random.key(0))
    qparams = quantize_params(params, cfg.model)
    prompt = [5, 3, 9, 250, 17]

    toks = list(prompt)
    for _ in range(8):
        logits, _ = forward(qparams, jnp.asarray([toks], jnp.int32), cfg.model)
        toks.append(int(jnp.argmax(logits[0, len(toks) - 1])))
    ref = toks[len(prompt):]

    out = InferenceEngine(cfg, params).generate([prompt], 8)[0]
    assert out == ref


def test_trainer_rejects_weight_quant():
    from orion_tpu.train import Trainer

    cfg = get_config(
        "tiny-llama", ["runtime.platform=cpu", "model.weight_quant=int8"]
    )
    with pytest.raises(ValueError, match="serving-only"):
        Trainer(cfg)
