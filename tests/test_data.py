"""Data pipeline tests: loader determinism, the native C++ reader vs the
numpy fallback, and end-to-end memmap training."""

import numpy as np
import pytest

from orion_tpu.config import DataConfig
from orion_tpu.data.loader import (
    MemmapLoader,
    SyntheticLoader,
    _NumpyReader,
)


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "tokens.u16"
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 50000, size=20_000, dtype=np.uint16)
    tokens.tofile(path)
    return str(path), tokens


def test_synthetic_deterministic_and_shifted():
    cfg = DataConfig(batch_size=4, seq_len=32)
    ldr = SyntheticLoader(cfg, 0, 1, vocab_size=256)
    b1, b2 = ldr.batch_at(7), ldr.batch_at(7)
    np.testing.assert_array_equal(b1["inputs"], b2["inputs"])
    # targets are inputs shifted by one
    b3 = ldr.batch_at(8)
    assert not np.array_equal(b1["inputs"], b3["inputs"])
    np.testing.assert_array_equal(b1["inputs"][:, 1:], b1["targets"][:, :-1])


@pytest.mark.parametrize("packed", [False, True])
def test_loaders_invariant_across_process_counts(token_file, packed):
    """Elastic-resume contract: the global batch at a step is identical
    whether served by 1 process or sliced across 2 — the data stream must
    not depend on process count (SURVEY.md §6 elastic recovery)."""
    from orion_tpu.data.loader import MemmapLoader, SyntheticLoader

    path, _ = token_file
    cfgs = [
        (SyntheticLoader,
         DataConfig(batch_size=4, seq_len=32, packed=packed),
         {"vocab_size": 256}),
        (MemmapLoader,
         DataConfig(source="memmap", path=path, batch_size=4, seq_len=32,
                    packed=packed, eos_token_id=0, use_native_loader=False),
         {"vocab_size": 256}),
    ]
    for cls, cfg, kw in cfgs:
        whole = cls(cfg, 0, 1, **kw).batch_at(5)
        lo = cls(cfg, 0, 2, **kw).batch_at(5)
        hi = cls(cfg, 1, 2, **kw).batch_at(5)
        for key in whole:
            np.testing.assert_array_equal(
                whole[key],
                np.concatenate([lo[key], hi[key]]),
                err_msg=f"{cls.__name__}.{key}",
            )


def test_native_reader_matches_numpy(token_file):
    path, tokens = token_file
    native = pytest.importorskip("orion_tpu.data.native")
    rdr = native.NativeReader(path, np.uint16)
    ref = _NumpyReader(path, np.dtype(np.uint16))
    assert len(rdr) == len(ref) == len(tokens)
    offs = np.asarray([0, 17, 5000, len(tokens) - 129])
    np.testing.assert_array_equal(rdr.gather(offs, 129), ref.gather(offs, 129))
    rdr.prefetch(offs, 129)  # smoke: readahead must not crash
    rdr.close()


def test_native_reader_bounds_check(token_file):
    path, tokens = token_file
    native = pytest.importorskip("orion_tpu.data.native")
    rdr = native.NativeReader(path, np.uint16)
    with pytest.raises(IndexError):
        rdr.gather(np.asarray([len(tokens) - 10]), 129)
    rdr.close()


@pytest.mark.parametrize("use_native", [True, False])
def test_memmap_loader_native_and_fallback_agree(token_file, use_native):
    path, _ = token_file
    cfg = DataConfig(source="memmap", path=path, batch_size=4, seq_len=64,
                     use_native_loader=use_native)
    ldr = MemmapLoader(cfg, 0, 1, vocab_size=50000)
    batch = ldr.batch_at(3)
    assert batch["inputs"].shape == (4, 64)
    np.testing.assert_array_equal(batch["inputs"][:, 1:],
                                  batch["targets"][:, :-1])
    # Same (seed, step) -> same windows regardless of reader backend.
    cfg2 = DataConfig(source="memmap", path=path, batch_size=4, seq_len=64,
                      use_native_loader=not use_native)
    ldr2 = MemmapLoader(cfg2, 0, 1, vocab_size=50000)
    np.testing.assert_array_equal(batch["inputs"],
                                  ldr2.batch_at(3)["inputs"])


def test_memmap_training_smoke(token_file):
    """train.py path over a real token file (memmap + native reader)."""
    import jax

    from orion_tpu.config import get_config
    from orion_tpu.train import Trainer

    path, _ = token_file
    cfg = get_config("tiny", [
        "runtime.platform=cpu",
        "data.source=memmap", f"data.path={path}", "data.batch_size=4",
        "data.seq_len=32", "model.vocab_size=50304",
        "train.num_steps=3", "train.log_interval=100",
        "optimizer.warmup_steps=1",
    ])
    t = Trainer(cfg)
    state, _ = t.restore_or_init()
    state, m = t.train_step(state, t.global_batch(0))
    assert np.isfinite(float(jax.device_get(m["loss"])))


# -- sequence packing ---------------------------------------------------------


def test_pack_rows_invariants():
    from orion_tpu.data.loader import pack_rows

    docs = [[np.arange(1, 6), np.arange(10, 14)],   # lens 5, 4 -> 4+3 pairs
            [np.arange(20, 40)]]                     # one long doc
    b = pack_rows(docs, seq_len=10)
    assert set(b) == {"inputs", "targets", "segment_ids", "positions",
                      "loss_mask"}
    # Row 0: doc 1 occupies 4 slots (seg 1), doc 2 occupies 3 (seg 2).
    np.testing.assert_array_equal(
        b["segment_ids"][0], [1, 1, 1, 1, 2, 2, 2, 0, 0, 0]
    )
    np.testing.assert_array_equal(
        b["positions"][0], [0, 1, 2, 3, 0, 1, 2, 0, 0, 0]
    )
    np.testing.assert_array_equal(
        b["loss_mask"][0], [1, 1, 1, 1, 1, 1, 1, 0, 0, 0]
    )
    # Targets are next-token within each document.
    np.testing.assert_array_equal(b["inputs"][0][:4], [1, 2, 3, 4])
    np.testing.assert_array_equal(b["targets"][0][:4], [2, 3, 4, 5])
    np.testing.assert_array_equal(b["inputs"][0][4:7], [10, 11, 12])
    np.testing.assert_array_equal(b["targets"][0][4:7], [11, 12, 13])
    # Long doc truncates to the row.
    assert b["loss_mask"][1].sum() == 10


def test_pack_rows_carries_truncated_doc_tail():
    """A doc crossing the row boundary resumes in the next row — the tail
    pairs are trained, not dropped (only the final row's overhang is lost)."""
    from orion_tpu.data.loader import pack_rows

    long = np.arange(100, 116)                      # 16 tokens, 15 pairs
    b = pack_rows([[long], []], seq_len=10)
    # Row 0: first 10 pairs of the doc.
    np.testing.assert_array_equal(b["inputs"][0], long[:10])
    np.testing.assert_array_equal(b["targets"][0], long[1:11])
    # Row 1: the carried tail resumes at token 10 — pair (110 -> 111) first,
    # so no pair is dropped or duplicated across the split.
    np.testing.assert_array_equal(b["inputs"][1][:5], long[10:15])
    np.testing.assert_array_equal(b["targets"][1][:5], long[11:16])
    assert b["loss_mask"][1].sum() == 5
    # The tail is its own segment with restarted positions.
    np.testing.assert_array_equal(b["segment_ids"][1][:5], [1] * 5)
    np.testing.assert_array_equal(b["positions"][1][:5], np.arange(5))


def test_pack_rows_masks_empty_rows():
    """A row with no packable document (all spans < 2 tokens) trains
    nothing: fully masked, segment 0 everywhere."""
    from orion_tpu.data.loader import pack_rows

    b = pack_rows([[np.array([7])], [np.array([1, 2, 3])]], seq_len=4)
    assert b["loss_mask"][0].sum() == 0
    assert (b["segment_ids"][0] == 0).all()
    assert b["loss_mask"][1].sum() == 2


def test_synthetic_packed_loader():
    from orion_tpu.config import DataConfig
    from orion_tpu.data import make_loader

    cfg = DataConfig(batch_size=4, seq_len=64, packed=True)
    loader = make_loader(cfg, vocab_size=251)
    b1, b2 = loader.batch_at(3), loader.batch_at(3)
    np.testing.assert_array_equal(b1["inputs"], b2["inputs"])  # deterministic
    assert b1["segment_ids"].max() >= 2       # actually multi-document
    assert (b1["loss_mask"].sum(1) > 48).all()  # rows mostly filled
    # Positions restart at every segment boundary.
    seg, pos = b1["segment_ids"][0], b1["positions"][0]
    starts = np.flatnonzero(np.diff(seg, prepend=seg[0] - 1) != 0)
    valid = seg > 0
    assert (pos[starts[valid[starts]]] == 0).all()


def test_memmap_packed_splits_at_eos(tmp_path):
    from orion_tpu.config import DataConfig
    from orion_tpu.data import make_loader

    rng = np.random.default_rng(0)
    toks = rng.integers(1, 250, size=50_000).astype(np.uint16)
    toks[::17] = 0    # sprinkle eos
    path = str(tmp_path / "t.u16")
    toks.tofile(path)
    cfg = DataConfig(source="memmap", path=path, batch_size=4, seq_len=32,
                     packed=True, eos_token_id=0, use_native_loader=False)
    loader = make_loader(cfg, vocab_size=251)
    b = loader.batch_at(5)
    assert b["segment_ids"].max() >= 2
    # No target may be a cross-document prediction: inside one segment the
    # (input, target) pairs chain (targets[i] == inputs[i+1]).
    seg, inp, tgt = b["segment_ids"][0], b["inputs"][0], b["targets"][0]
    for i in range(len(seg) - 1):
        if seg[i] != 0 and seg[i] == seg[i + 1]:
            assert tgt[i] == inp[i + 1]


def test_packed_training_runs_and_learns():
    """End-to-end: packed batches through the jit train step on a dp mesh;
    the synthetic structure is learnable, so loss must fall."""
    from orion_tpu.config import get_config
    from orion_tpu.train import Trainer

    cfg = get_config(
        "tiny-llama",
        ["runtime.platform=cpu", "data.packed=true", "data.batch_size=8",
         "parallel.dp=2", "train.num_steps=30", "train.log_interval=1000",
         "optimizer.warmup_steps=3"],
    )
    hist = Trainer(cfg).fit()
    assert hist[-1].loss < hist[0].loss - 0.3, (hist[0].loss, hist[-1].loss)


def test_packed_training_composes_with_pipeline():
    """Packed rows x pp (r4 restriction lifted): pipelined packed training
    matches the single-layout packed trajectory — segment masks and
    per-doc positions slice per microbatch and are looked up per stage."""
    import jax as _jax
    import numpy as _np

    from orion_tpu.config import get_config
    from orion_tpu.train import Trainer

    def run(axes):
        overrides = [
            "runtime.platform=cpu", "data.packed=true", "data.batch_size=4",
            "data.seq_len=32", "train.num_steps=3", "train.log_interval=100",
            "optimizer.warmup_steps=1",
        ] + [f"parallel.{k}={v}" for k, v in axes.items()]
        t = Trainer(get_config("tiny-llama", overrides))
        state, _ = t.restore_or_init()
        losses = []
        for step in range(3):
            state, m = t.train_step(state, t.global_batch(step))
            losses.append(float(_jax.device_get(m["loss"])))
        return losses

    base = run({})
    pp = run({"pp": 2, "pp_microbatches": 2})
    _np.testing.assert_allclose(pp, base, rtol=2e-4)


def test_pack_rows_skips_degenerate_docs():
    """A <2-token document must be skipped, not end the row's packing."""
    from orion_tpu.data.loader import pack_rows

    b = pack_rows([[np.array([7]), np.array([1, 2, 3, 4])]], seq_len=8)
    assert b["loss_mask"][0].sum() == 3          # the 4-token doc packed
    np.testing.assert_array_equal(b["inputs"][0][:3], [1, 2, 3])


import pytest as _pytest


@_pytest.mark.parametrize("method", ["ring", "ring_striped", "ulysses"])
def test_packed_composes_with_sequence_parallelism(method):
    """Packed batches under sp=2 (segment ids + custom positions sharded —
    and, for ring_striped, permuted — over the sequence) match the sp=1
    loss trajectory for every sequence method."""
    from orion_tpu.config import get_config
    from orion_tpu.train import Trainer

    def run(axes):
        cfg = get_config(
            "tiny-llama",
            ["runtime.platform=cpu", "data.packed=true", "data.batch_size=8",
             "data.seq_len=64", "train.num_steps=3",
             "train.log_interval=1000", "optimizer.warmup_steps=1",
             f"parallel.sequence_method={method}"] + axes,
        )
        return Trainer(cfg).fit()

    base = run(["parallel.dp=4"])
    sp = run(["parallel.dp=2", "parallel.sp=2"])
    for a, b in zip(base, sp):
        np.testing.assert_allclose(a.loss, b.loss, rtol=2e-3, atol=2e-3)


def test_pack_rows_drop_counter_observable():
    """The bounded token loss at carry-group resets (ADVICE r4) is tallied
    in loader.pack_stats so it can be monitored at scale."""
    import numpy as np

    from orion_tpu.data import loader as L

    L.pack_stats["dropped_tokens"] = 0
    long = np.arange(25, dtype=np.int32)       # 24 pairs >> seq_len
    # Row 0 packs 10 pairs, tail (14 pairs) carries; carry_group=1 resets
    # the carry at row 1 -> the whole tail is dropped and tallied.
    L.pack_rows([[long], []], seq_len=10, carry_group=1)
    assert L.pack_stats["dropped_tokens"] == 14
    # No reset boundary crossed with the carry non-empty: nothing tallied.
    L.pack_stats["dropped_tokens"] = 0
    L.pack_rows([[long], []], seq_len=10, carry_group=2)
    assert L.pack_stats["dropped_tokens"] == 0
