"""Data pipeline tests: loader determinism, the native C++ reader vs the
numpy fallback, and end-to-end memmap training."""

import numpy as np
import pytest

from orion_tpu.config import DataConfig
from orion_tpu.data.loader import (
    MemmapLoader,
    SyntheticLoader,
    _NumpyReader,
)


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "tokens.u16"
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 50000, size=20_000, dtype=np.uint16)
    tokens.tofile(path)
    return str(path), tokens


def test_synthetic_deterministic_and_shifted():
    cfg = DataConfig(batch_size=4, seq_len=32)
    ldr = SyntheticLoader(cfg, 0, 1, vocab_size=256)
    b1, b2 = ldr.batch_at(7), ldr.batch_at(7)
    np.testing.assert_array_equal(b1["inputs"], b2["inputs"])
    # targets are inputs shifted by one
    b3 = ldr.batch_at(8)
    assert not np.array_equal(b1["inputs"], b3["inputs"])
    np.testing.assert_array_equal(b1["inputs"][:, 1:], b1["targets"][:, :-1])


def test_native_reader_matches_numpy(token_file):
    path, tokens = token_file
    native = pytest.importorskip("orion_tpu.data.native")
    rdr = native.NativeReader(path, np.uint16)
    ref = _NumpyReader(path, np.dtype(np.uint16))
    assert len(rdr) == len(ref) == len(tokens)
    offs = np.asarray([0, 17, 5000, len(tokens) - 129])
    np.testing.assert_array_equal(rdr.gather(offs, 129), ref.gather(offs, 129))
    rdr.prefetch(offs, 129)  # smoke: readahead must not crash
    rdr.close()


def test_native_reader_bounds_check(token_file):
    path, tokens = token_file
    native = pytest.importorskip("orion_tpu.data.native")
    rdr = native.NativeReader(path, np.uint16)
    with pytest.raises(IndexError):
        rdr.gather(np.asarray([len(tokens) - 10]), 129)
    rdr.close()


@pytest.mark.parametrize("use_native", [True, False])
def test_memmap_loader_native_and_fallback_agree(token_file, use_native):
    path, _ = token_file
    cfg = DataConfig(source="memmap", path=path, batch_size=4, seq_len=64,
                     use_native_loader=use_native)
    ldr = MemmapLoader(cfg, 0, 1, vocab_size=50000)
    batch = ldr.batch_at(3)
    assert batch["inputs"].shape == (4, 64)
    np.testing.assert_array_equal(batch["inputs"][:, 1:],
                                  batch["targets"][:, :-1])
    # Same (seed, step) -> same windows regardless of reader backend.
    cfg2 = DataConfig(source="memmap", path=path, batch_size=4, seq_len=64,
                      use_native_loader=not use_native)
    ldr2 = MemmapLoader(cfg2, 0, 1, vocab_size=50000)
    np.testing.assert_array_equal(batch["inputs"],
                                  ldr2.batch_at(3)["inputs"])


def test_memmap_training_smoke(token_file):
    """train.py path over a real token file (memmap + native reader)."""
    import jax

    from orion_tpu.config import get_config
    from orion_tpu.train import Trainer

    path, _ = token_file
    cfg = get_config("tiny", [
        "runtime.platform=cpu",
        "data.source=memmap", f"data.path={path}", "data.batch_size=4",
        "data.seq_len=32", "model.vocab_size=50304",
        "train.num_steps=3", "train.log_interval=100",
        "optimizer.warmup_steps=1",
    ])
    t = Trainer(cfg)
    state, _ = t.restore_or_init()
    state, m = t.train_step(state, t.global_batch(0))
    assert np.isfinite(float(jax.device_get(m["loss"])))
