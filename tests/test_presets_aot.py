"""AOT lowering checks for the flagship presets (VERDICT r2 item 9).

The judged configs (BASELINE.json 2-4) are full-size Llama-3-8B / 70B /
Mixtral models on 64-chip meshes — unbuildable on the dev box, but their
train step can be TRACED AND LOWERED symbolically: abstract state in, jit
.lower() out. This proves the flagship presets are demonstrably runnable
programs (shapes, shardings, scan/remat structure, collective insertion all
elaborate without error) rather than just declared dataclasses. The mesh is
shrunk to the 8 fake CPU devices; every model dimension stays full-size.
"""

import jax
import pytest

from orion_tpu.config import get_config
from orion_tpu.train import Trainer

# Revived on jax-0.4.37 boxes by the round-6 compat shims (previously a
# collection error), but too heavy for the tier-1 CPU budget — the serving
# stack (test_infer / test_prefix_cache) owns that budget this round. Runs
# in the full tier (no `-m "not slow"`).
pytestmark = pytest.mark.slow



@pytest.mark.parametrize(
    "preset,axes",
    [
        ("llama3-8b-dp", {"dp": 8}),
        ("llama3-70b-fsdp", {"fsdp": 8}),
        ("mixtral-8x7b-ep", {"fsdp": 2, "ep": 4}),
        ("mistral-7b-fsdp", {"fsdp": 8}),
        ("qwen2-7b-fsdp", {"fsdp": 8}),
        # Gemma-2: interleaved local/global grouped layer scan, post-norms,
        # dual softcaps at full 9B size.
        ("gemma2-9b-fsdp", {"fsdp": 8}),
        # Long-context flagship: full 262144-token sequence through the
        # striped ring (S % sp^2 == 0 holds at sp=8 too).
        ("llama3-8b-256k-ring", {"sp": 8}),
        # Interleaved virtual-stage pipeline at full 70B size: pp=4, V=4
        # (80 layers -> 16 chunks of 5, chunk c on device c mod 4),
        # composed with ZeRO-3 on fsdp=2 (round-5 schedule).
        ("llama3-70b-fsdp", {"pp": 4, "fsdp": 2, "pp_microbatches": 4,
                             "pp_schedule": "interleaved",
                             "pp_virtual_stages": 4}),
    ],
)
def test_flagship_preset_train_step_lowers(cpu_devices, preset, axes):
    overrides = ["runtime.platform=cpu"] + [
        f"parallel.{k}={v}" for k, v in axes.items()
    ]
    # dp=1 for the axes not listed: apply_overrides only sets what's given;
    # the presets' 64-way axes are replaced wholesale.
    for axis in ("dp", "fsdp", "tp", "pp", "sp", "ep"):
        if axis not in axes:
            overrides.append(f"parallel.{axis}=1")
    cfg = get_config(preset, overrides)
    t = Trainer(cfg)
    state = t.abstract_state()
    batch_shapes = jax.eval_shape(lambda: t.loader.batch_at(0))
    lowered = t.train_step.lower(state, batch_shapes)
    hlo = lowered.as_text()
    assert "ENTRY" in hlo or "func.func" in hlo  # non-empty lowered module


def test_serving_preset_decode_program_lowers(cpu_devices):
    """BASELINE config 5 (llama3-8b-infer): the fused decode-window program
    lowers at full model size with abstract params/cache — the serving path
    is a demonstrably compilable program, not just a declared preset."""
    from functools import partial

    from orion_tpu.infer.kv_cache import init_cache, pages_per_seq
    from orion_tpu.infer.runner import decode_window
    from orion_tpu.models import init_params

    cfg = get_config("llama3-8b-infer", ["runtime.platform=cpu"])
    mcfg, icfg = cfg.model, cfg.inference
    B, W = icfg.max_batch_size, icfg.decode_window
    pps = pages_per_seq(icfg)

    params = jax.eval_shape(lambda: init_params(mcfg, jax.random.key(0)))
    cache = jax.eval_shape(lambda: init_cache(mcfg, icfg))
    keys = jax.eval_shape(
        lambda: jax.random.split(jax.random.key(0), W)
    )
    i32 = lambda *s: jax.ShapeDtypeStruct(s, "int32")
    common = (
        params, cache, i32(B), i32(B), i32(B, pps),
        jax.ShapeDtypeStruct((B,), "bool"), keys,
    )
    # Greedy all-defaults specialization (what the bench decode compiles).
    lowered = jax.jit(
        partial(
            decode_window, cfg=mcfg, max_seq_len=icfg.max_seq_len,
            temperature=icfg.temperature, top_k=icfg.top_k,
            top_p=icfg.top_p,
        ),
        donate_argnums=(1,),
    ).lower(*common)
    hlo = lowered.as_text()
    assert "ENTRY" in hlo or "func.func" in hlo
    # The general per-request sampling program (traced [B] params, full
    # top-k/top-p machinery at V=128256) — greedy is a subgraph of this.
    f32 = lambda *s: jax.ShapeDtypeStruct(s, "float32")
    lowered = jax.jit(
        partial(decode_window, cfg=mcfg, max_seq_len=icfg.max_seq_len),
        donate_argnums=(1,),
    ).lower(*common, f32(B), i32(B), f32(B))
    hlo = lowered.as_text()
    assert "ENTRY" in hlo or "func.func" in hlo
