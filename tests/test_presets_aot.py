"""AOT lowering checks for the flagship presets (VERDICT r2 item 9).

The judged configs (BASELINE.json 2-4) are full-size Llama-3-8B / 70B /
Mixtral models on 64-chip meshes — unbuildable on the dev box, but their
train step can be TRACED AND LOWERED symbolically: abstract state in, jit
.lower() out. This proves the flagship presets are demonstrably runnable
programs (shapes, shardings, scan/remat structure, collective insertion all
elaborate without error) rather than just declared dataclasses. The mesh is
shrunk to the 8 fake CPU devices; every model dimension stays full-size.
"""

import jax
import pytest

from orion_tpu.config import get_config
from orion_tpu.train import Trainer


@pytest.mark.parametrize(
    "preset,axes",
    [
        ("llama3-8b-dp", {"dp": 8}),
        ("llama3-70b-fsdp", {"fsdp": 8}),
        ("mixtral-8x7b-ep", {"fsdp": 2, "ep": 4}),
    ],
)
def test_flagship_preset_train_step_lowers(cpu_devices, preset, axes):
    overrides = ["runtime.platform=cpu"] + [
        f"parallel.{k}={v}" for k, v in axes.items()
    ]
    # dp=1 for the axes not listed: apply_overrides only sets what's given;
    # the presets' 64-way axes are replaced wholesale.
    for axis in ("dp", "fsdp", "tp", "pp", "sp", "ep"):
        if axis not in axes:
            overrides.append(f"parallel.{axis}=1")
    cfg = get_config(preset, overrides)
    t = Trainer(cfg)
    state = t.abstract_state()
    batch_shapes = jax.eval_shape(lambda: t.loader.batch_at(0))
    lowered = t.train_step.lower(state, batch_shapes)
    hlo = lowered.as_text()
    assert "ENTRY" in hlo or "func.func" in hlo  # non-empty lowered module
