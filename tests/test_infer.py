"""Inference-tier tests (SURVEY.md §5): the continuous-batching engine fed
request mixes must produce exactly the tokens of single-request generation,
and the paged KV cache must recycle pages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from orion_tpu.config import get_config
from orion_tpu.infer import InferenceEngine
from orion_tpu.infer.sampling import sample
from orion_tpu.models import forward, init_params

INFER_OVERRIDES = [
    "inference.max_seq_len=128",
    "inference.page_size=16",
    "inference.num_pages=32",
    "inference.max_batch_size=4",
    "inference.prefill_chunk=16",
    "inference.max_new_tokens=8",
]


def _setup(preset="tiny-llama", overrides=()):
    cfg = get_config(preset, INFER_OVERRIDES + list(overrides))
    params = init_params(cfg.model, jax.random.key(0))
    return cfg, params


def _ref_generate(params, mcfg, prompt, n):
    """Autoregressive greedy generation via the full training forward."""
    toks = list(prompt)
    for _ in range(n):
        logits, _ = forward(params, jnp.asarray([toks], jnp.int32), mcfg)
        toks.append(int(jnp.argmax(logits[0, len(toks) - 1])))
    return toks[len(prompt):]


@pytest.mark.parametrize(
    "preset", ["tiny-llama", "tiny", "tiny-mixtral", "tiny-gemma2"]
)
def test_engine_matches_full_forward(preset):
    """Paged-cache decode must reproduce the no-cache forward exactly
    (greedy), across the model zoo: RoPE/GQA, learned-pos/LayerNorm, MoE,
    and Gemma-2's interleaved local/global windows + post-norms + dual
    softcaps (full-context pages with per-layer masks)."""
    cfg, params = _setup(preset)
    prompt = [5, 3, 9, 250, 17]
    ref = _ref_generate(params, cfg.model, prompt, 8)
    out = InferenceEngine(cfg, params).generate([prompt], 8)[0]
    assert out == ref


def test_gemma2_engine_pallas_matches_xla_beyond_window():
    """Gemma-2 serving on the Pallas path (flash prefill + ragged paged
    decode with PER-LAYER windows through the grouped layer scan,
    interpret mode) must produce the xla path's tokens — generating PAST
    the sliding window, the hard case for the paged kernel's window/page
    clamp when full-context pages are kept for the global layers."""
    import dataclasses

    cfg, params = _setup("tiny-gemma2")
    prompt = [5, 3, 9, 250, 17]
    n = 24                                  # context 29 >> window 16
    ref = InferenceEngine(cfg, params).generate([prompt], n)[0]
    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    out = InferenceEngine(pcfg, params).generate([prompt], n)[0]
    assert out == ref


# slow (tier-1 budget, round 8): softcap serving stays pinned in
# tier-1 by test_gemma2_engine_pallas_matches_xla_beyond_window and
# test_engine_matches_full_forward[tiny-gemma2].
@pytest.mark.slow
def test_gemma2_engine_softcap_regime():
    """Serving must apply the attention logit softcap (regression: prefill
    and the xla decode fallback silently omitted it). Tiny random weights
    never reach the cap, so scale the q/k projections until logits live in
    the tanh-saturating regime — engine tokens must still equal the
    training forward's."""
    import jax.numpy as jnp

    cfg, params = _setup("tiny-gemma2")
    boost = jnp.asarray(6.0, params["blocks"]["attn"]["wq"].dtype)
    params = dict(params)
    params["blocks"] = jax.tree.map(lambda x: x, params["blocks"])
    params["blocks"]["attn"] = dict(params["blocks"]["attn"])
    params["blocks"]["attn"]["wq"] = params["blocks"]["attn"]["wq"] * boost
    params["blocks"]["attn"]["wk"] = params["blocks"]["attn"]["wk"] * boost
    prompt = [5, 3, 9, 250, 17]
    ref = _ref_generate(params, cfg.model, prompt, 8)
    out = InferenceEngine(cfg, params).generate([prompt], 8)[0]
    assert out == ref


@pytest.mark.slow  # 24 full-forward reference decodes, ~40s on the CPU tier
def test_gemma2_engine_beyond_window():
    """Gemma-2 serving past the sliding window: local layers mask to the
    last W positions while global layers read the whole history (pages
    must NOT roll — page_window is None under a pattern); still exactly
    reproduces the full forward."""
    cfg, params = _setup("tiny-gemma2")
    eng = InferenceEngine(cfg, params)
    assert eng.page_window is None          # full-context pages kept
    prompt = [5, 3, 9, 250, 17]
    n = 24                                  # context 29 >> window 16
    ref = _ref_generate(params, cfg.model, prompt, n)
    out = eng.generate([prompt], n)[0]
    assert out == ref


def test_engine_pallas_kernels_match_xla():
    """The full serving path on Pallas kernels (flash prefill + ragged paged
    decode, interpret mode on CPU) must produce the xla path's tokens."""
    cfg, params = _setup()
    import dataclasses

    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    prompt = [5, 3, 9, 250, 17]
    ref = InferenceEngine(cfg, params).generate([prompt], 6)[0]
    out = InferenceEngine(pcfg, params).generate([prompt], 6)[0]
    assert out == ref


def test_continuous_batching_preserves_outputs():
    """Batched serving (with queueing beyond max_batch_size) must not change
    any request's tokens."""
    cfg, params = _setup()
    prompts = [
        [5, 3, 9],
        [250, 17, 4, 8, 100, 42],
        [7] * 20,
        [1, 2],
        [99, 98, 97, 96],
        [11, 13, 17, 19, 23],
    ]  # 6 requests > max_batch_size=4 forces admission queueing
    singles = [
        InferenceEngine(cfg, params).generate([p], 6)[0] for p in prompts
    ]
    batched = InferenceEngine(cfg, params).generate(prompts, 6)
    assert batched == singles


def test_mid_flight_admission():
    """A request submitted while another is decoding joins the batch without
    disturbing either result."""
    cfg, params = _setup()
    p1, p2 = [5, 3, 9, 250, 17], [42, 7]
    ref1 = InferenceEngine(cfg, params).generate([p1], 8)[0]
    ref2 = InferenceEngine(cfg, params).generate([p2], 8)[0]

    eng = InferenceEngine(cfg, params)
    eng.submit(p1, 8)
    finished = []
    finished += eng.step()
    finished += eng.step()
    eng.submit(p2, 8)
    while eng.has_work():
        finished += eng.step()
    by_rid = sorted(finished, key=lambda r: r.rid)
    assert [r.generated for r in by_rid] == [ref1, ref2]


def test_sharded_engine_matches_unsharded():
    """The engine is mesh-agnostic (the params' shardings decide): serving
    with tp-sharded params over the fake 8-CPU-device mesh must produce the
    unsharded engine's exact tokens (VERDICT r2: sharded inference was
    untested)."""
    from orion_tpu.config import ParallelConfig
    from orion_tpu.models.transformer import param_logical_axes
    from orion_tpu.parallel.sharding import param_shardings
    from orion_tpu.runtime import build_mesh

    cfg, params = _setup()
    prompt = [5, 3, 9, 250, 17]
    ref = InferenceEngine(cfg, params).generate([prompt], 6)[0]

    mesh = build_mesh(
        ParallelConfig(tp=2, dp=2), devices=jax.devices("cpu")[:4]
    )
    shardings = param_shardings(mesh, param_logical_axes(cfg.model))
    sharded = jax.device_put(params, shardings)
    out = InferenceEngine(cfg, sharded).generate([prompt], 6)[0]
    assert out == ref


@pytest.mark.parametrize("kv_quant", [
    None,
    # slow (tier-1 budget, round 8): tp x pallas stays in tier-1 via
    # the None variant; the int8 cross runs in the slow tier.
    pytest.param("int8", marks=pytest.mark.slow),
])
def test_sharded_engine_pallas_matches_unsharded(kv_quant):
    """Serving on the PALLAS path with tp-sharded params (VERDICT r4
    missing #3): flash prefill and the ragged paged decode kernel run
    under head-sharded shard_maps (a bare pallas_call would gather the
    tp-sharded operands), the KV pool lives sharded over kv heads, and
    the served tokens equal the unsharded engine's exactly — including
    the int8 scale pools riding the same sharding."""
    import dataclasses

    from orion_tpu.config import ParallelConfig
    from orion_tpu.models.transformer import param_logical_axes
    from orion_tpu.parallel.sharding import param_shardings
    from orion_tpu.runtime import build_mesh

    overrides = [] if kv_quant is None else [f"inference.kv_quant={kv_quant}"]
    cfg, params = _setup(overrides=overrides)
    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    prompts = [[5, 3, 9, 250, 17], [42, 7]]
    ref = InferenceEngine(pcfg, params).generate(prompts, 6)

    mesh = build_mesh(
        ParallelConfig(tp=2, dp=2), devices=jax.devices("cpu")[:4]
    )
    shardings = param_shardings(mesh, param_logical_axes(cfg.model))
    sharded = jax.device_put(params, shardings)
    eng = InferenceEngine(pcfg, sharded)
    assert eng.mesh is not None              # tp mesh detected from params
    k_shard = eng.cache["k"].sharding
    assert k_shard.spec[1] == "tp"           # pool sharded over kv heads
    out = eng.generate(prompts, 6)
    assert out == ref


# slow (tier-1 budget, round 8): the unsharded gemma2-beyond-window
# and the sharded llama engines keep both halves of this composition
# in tier-1; the full cross stays in the slow tier.
@pytest.mark.slow
def test_sharded_engine_pallas_gemma2_beyond_window():
    """The hardest serving composition: tp-sharded params x Pallas kernels
    x Gemma-2's interleaved per-layer windows, generating PAST the sliding
    window — the paged kernel's window/page clamp and the flash prefill's
    per-layer masks must hold under the head-sharded shard_map exactly as
    unsharded."""
    import dataclasses

    from orion_tpu.config import ParallelConfig
    from orion_tpu.models.transformer import param_logical_axes
    from orion_tpu.parallel.sharding import param_shardings
    from orion_tpu.runtime import build_mesh

    cfg, params = _setup("tiny-gemma2")
    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    prompt = [5, 3, 9, 250, 17]
    n = 24                                   # context 29 >> window 16
    ref = InferenceEngine(pcfg, params).generate([prompt], n)[0]

    mesh = build_mesh(ParallelConfig(tp=2), devices=jax.devices("cpu")[:2])
    shardings = param_shardings(mesh, param_logical_axes(cfg.model))
    sharded = jax.device_put(params, shardings)
    out = InferenceEngine(pcfg, sharded).generate([prompt], n)[0]
    assert out == ref


def test_sharded_engine_pallas_rejects_indivisible_heads():
    """tp that does not divide the kv heads must fail loudly at engine
    construction, not silently gather or miscompute."""
    import dataclasses

    from orion_tpu.config import ParallelConfig
    from orion_tpu.models.transformer import param_logical_axes
    from orion_tpu.parallel.sharding import param_shardings
    from orion_tpu.runtime import build_mesh

    cfg, params = _setup()                  # tiny-llama: K=2 kv heads
    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    mesh = build_mesh(ParallelConfig(tp=4), devices=jax.devices("cpu")[:4])
    axes = param_logical_axes(cfg.model)
    try:
        shardings = param_shardings(mesh, axes)
        sharded = jax.device_put(params, shardings)
    except Exception:
        pytest.skip("tp=4 param sharding itself rejects this tiny model")
    with pytest.raises(ValueError, match="divisible"):
        InferenceEngine(pcfg, sharded)


def test_burst_admission_prefills_in_one_dispatch():
    """A burst of same-bucket admissions must be served by ONE batched
    prefill dispatch, not one per prompt (VERDICT r2 item 4)."""
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    calls = []
    orig = eng._prefill

    def counting(*args):
        calls.append(args[2].shape)  # tokens [Nb, S_pad]
        return orig(*args)

    eng._prefill = counting
    prompts = [[5, 3, 9], [1, 2], [7, 8, 9, 10], [4]]
    for p in prompts:
        eng.submit(p, 4)
    eng.step()
    assert len(calls) == 1, calls
    assert calls[0][0] == 4, calls  # all four prompts in one batch


# slow (tier-1 budget, round 8): the one-ragged-dispatch admission
# shape is also asserted (xla side) by
# test_mixed_length_burst_xla_keeps_per_bucket_dispatches.
@pytest.mark.slow
def test_mixed_length_burst_prefills_in_one_ragged_dispatch():
    """On the pallas path, prompts spanning DIFFERENT buckets admit in a
    single ragged prefill dispatch (VERDICT r3 item 7): rows pad to the
    burst max, padding blocks skip via segment ids, and outputs equal
    single-request generation. The xla path keeps per-bucket dispatches
    (it has no block skip, so a short row would pay burst-max O(S^2))."""
    cfg, params = _setup(
        overrides=["model.kernels=pallas_interpret"])
    eng = InferenceEngine(cfg, params)
    calls = []
    orig = eng._prefill

    def counting(*args):
        calls.append(args[2].shape)  # tokens [Nb, S_pad]
        return orig(*args)

    eng._prefill = counting
    prompts = [[5, 3, 9], list(range(1, 21)), list(range(7, 47))]
    rids = [eng.submit(p, 4) for p in prompts]
    done = list(eng.step())
    assert len(calls) == 1, calls            # one dispatch, three buckets
    assert calls[0][1] == 48                 # burst max bucket (40 -> 48)
    while eng.has_work():
        done += eng.step()
    out = {r.rid: list(r.generated) for r in done}
    for p, rid in zip(prompts, rids):
        solo = InferenceEngine(cfg, params).generate([p], 4)[0]
        assert out[rid][:4] == solo


def test_mixed_length_burst_xla_keeps_per_bucket_dispatches():
    cfg, params = _setup()          # default kernels: xla
    eng = InferenceEngine(cfg, params)
    calls = []
    orig = eng._prefill

    def counting(*args):
        calls.append(args[2].shape)
        return orig(*args)

    eng._prefill = counting
    for p in [[5, 3, 9], list(range(1, 21)), list(range(7, 47))]:
        eng.submit(p, 2)
    eng.step()
    assert len(calls) == 3, calls   # one dispatch per bucket (16/32/48)
    assert sorted(c[1] for c in calls) == [16, 32, 48]


def test_decode_window_autotune_grows_and_preserves_tokens():
    """With autotune on and an unreachable host-share target, the window
    doubles every decoded step up to decode_window_max — and the served
    tokens are identical to the fixed-window engine (greedy decode is
    window-size invariant; VERDICT r4 weak #6)."""
    cfg, params = _setup()
    ref = InferenceEngine(cfg, params).generate([[5, 3, 9, 250, 17]], 8)[0]
    acfg, _ = _setup(overrides=[
        "inference.decode_window=2",
        "inference.decode_window_autotune=true",
        "inference.decode_window_max=16",
        "inference.decode_host_share_target=0.0",
    ])
    eng = InferenceEngine(acfg, params)
    out = eng.generate([[5, 3, 9, 250, 17]], 8)[0]
    assert out == ref
    assert eng.decode_window > 2            # grew from the measured split
    assert eng.decode_window <= 16
    t = eng.reset_timing()
    assert t["prefill_s"] > 0.0             # admission burst has its own bucket


def test_decode_window_autotune_shrinks_on_low_host_share():
    """The autotune is no longer growth-only: when the per-step host share
    falls below a quarter of the target, the window halves (hysteresis
    band [target/4, target] is stable), flooring at the configured
    inference.decode_window — so a load drop is not stuck with a doubled
    window's ITL forever. Driven directly through the measured-split hook
    so the decision rule is pinned, not the CPU timing."""
    acfg, params = _setup(overrides=[
        "inference.decode_window=2",
        "inference.decode_window_autotune=true",
        "inference.decode_window_max=16",
    ])
    eng = InferenceEngine(acfg, params)
    eng.decode_window = 16
    # Host share 0.01 < target 0.25 / 4: halve.
    eng._dev_span, eng._prefill_span = 0.99, 0.0
    eng._autotune_window(1.0)
    assert eng.decode_window == 8
    # In the hysteresis band [target/4, target]: hold.
    eng._dev_span = 0.9
    eng._autotune_window(1.0)
    assert eng.decode_window == 8
    # Above target: grow (the original path, bounded by the max).
    eng._dev_span = 0.5
    eng._autotune_window(1.0)
    assert eng.decode_window == 16
    # Shrink floors at the CONFIGURED window, never below.
    eng.decode_window = 2
    eng._dev_span = 0.99
    eng._autotune_window(1.0)
    assert eng.decode_window == 2
    # The current window is surfaced with the timing drain.
    assert eng.reset_timing()["decode_window"] == 2


def test_autotune_excludes_first_post_resize_step():
    """Satellite (ADVICE r5): a window resize changes the [W, B] decode
    shape, and the NEXT decode step's spans carry the retrace/recompile
    cost — that step must be excluded from the tuner, so one resize can
    never cascade into a second, spurious one off the compile's skewed
    host/device split. With an unreachable target (0.0: every evaluated
    step wants to grow) the window therefore grows at most every OTHER
    decoded step."""
    acfg, params = _setup(overrides=[
        "inference.decode_window=2",
        "inference.decode_window_autotune=true",
        "inference.decode_window_max=16",
        "inference.decode_host_share_target=0.0",
    ])
    eng = InferenceEngine(acfg, params)
    eng.submit([5, 3, 9, 250, 17], 14)
    grew = []
    while eng.has_work():
        before = eng.decode_window
        eng.step()
        grew.append(eng.decode_window != before)
    assert any(grew), grew                    # the tuner did act
    assert not any(a and b for a, b in zip(grew, grew[1:])), (
        "window resized on consecutive decoded steps: the post-resize "
        "recompile step fed the tuner", grew,
    )
    # Unit check: the resize itself is what arms the exclusion.
    eng2 = InferenceEngine(acfg, params)
    eng2._dev_span, eng2._prefill_span = 0.5, 0.0
    assert not eng2._autotune_skip
    eng2._autotune_window(1.0)
    assert eng2.decode_window == 4
    assert eng2._autotune_skip


def test_wasted_decode_fraction_pinned_mixed_lengths():
    """The device/host split now carries the decode-waste tally: at a mixed
    max_new_tokens trace with W=8, the slot finishing after 1 decoded token
    burns exactly W-1 garbage steps and the full-length slot burns the
    post-EOS remainder — pinned, so the decode_window tradeoff is
    observable data (VERDICT r4 weak #6)."""
    cfg, params = _setup()       # decode_window=8 via INFER_OVERRIDES? no:
    assert cfg.inference.decode_window == 8
    eng = InferenceEngine(cfg, params)
    eng.submit([5, 3, 9], 2)     # 1 prefill token + 1 decode -> done at j=0
    eng.submit([42, 7], 8)       # 1 prefill + 7 decode -> done at j=6
    while eng.has_work():
        eng.step()
    t = eng.reset_timing()
    assert t["slot_steps"] == 16, t         # one window, two active slots
    assert t["wasted_steps"] == 8, t        # 7 (short slot) + 1 (tail)


def test_eos_stops_generation():
    cfg, params = _setup()
    prompt = [5, 3, 9]
    free_run = InferenceEngine(cfg, params).generate([prompt], 8)[0]
    eos = free_run[2]  # treat the 3rd generated token as EOS
    out = InferenceEngine(cfg, params, eos_id=eos).generate([prompt], 8)[0]
    assert out == free_run[:3]


def test_pages_recycled_and_pool_exhaustion_queues():
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    eng.generate([[7] * 20, [1, 2, 3], [4, 5]], 6)
    assert eng.alloc.free_pages == cfg.inference.num_pages - 1  # page 0 scratch

    # A prompt longer than the context window is rejected at submit.
    with pytest.raises(ValueError):
        eng.submit([1] * 200, 4)


def test_oversized_prompt_rejected_at_submit():
    """A prompt whose pages can never fit the pool raises instead of
    queueing forever."""
    cfg, params = _setup(overrides=["inference.num_pages=4"])
    eng = InferenceEngine(cfg, params)
    with pytest.raises(ValueError, match="pages"):
        eng.submit([1] * 40, 4)


def test_bad_sampling_overrides_rejected_at_submit():
    """Out-of-range per-request sampling params raise at submit() instead of
    silently clamping / degenerating mid-decode."""
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    with pytest.raises(ValueError, match="temperature"):
        eng.submit([1, 2, 3], 2, temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit([1, 2, 3], 2, top_k=cfg.model.vocab_size + 1)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit([1, 2, 3], 2, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit([1, 2, 3], 2, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit([1, 2, 3], 2, top_p=1.5)
    # In-range values still queue.
    eng.submit([1, 2, 3], 2, temperature=0.7, top_k=0, top_p=1.0)


def test_default_valued_overrides_stay_on_fast_program():
    """Explicitly passing the engine-default sampling values is normalized to
    'no override': the batch must keep the specialized greedy decode program
    (no sort-based sampling switch)."""
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    icfg = cfg.inference
    rid = eng.submit([1, 2, 3], 2, temperature=icfg.temperature,
                     top_k=icfg.top_k, top_p=icfg.top_p)
    req = eng.waiting[-1]
    assert req.rid == rid
    assert req.temperature is None and req.top_k is None and req.top_p is None


def test_kv_int8_xla_and_pallas_paths_agree():
    """Under inference.kv_quant=int8 the xla gather path and the pallas
    in-kernel path quantize identically (same symmetric per-token-per-head
    rule), so the served tokens must match exactly."""
    cfg, params = _setup(overrides=["inference.kv_quant=int8"])
    import dataclasses

    prompt = [5, 3, 9, 250, 17]
    out_x = InferenceEngine(cfg, params).generate([prompt], 8)[0]
    pcfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kernels="pallas_interpret")
    )
    out_p = InferenceEngine(pcfg, params).generate([prompt], 8)[0]
    assert out_x == out_p


def test_kv_int8_tracks_unquantized_generation():
    """int8 KV is ~1% per-element error; on a random tiny model the greedy
    argmax stream should track the unquantized engine for at least the
    first tokens (and must run, recycle pages, and stay finite)."""
    cfg, params = _setup()
    qcfg, _ = _setup(overrides=["inference.kv_quant=int8"])
    ref = InferenceEngine(cfg, params).generate([[5, 3, 9, 250, 17]], 6)[0]
    got = InferenceEngine(qcfg, params).generate([[5, 3, 9, 250, 17]], 6)[0]
    assert len(got) == len(ref)
    assert got[0] == ref[0]  # first decode step off the prefill cache


def test_kv_int8_batched_serving_and_page_recycling():
    """Continuous batching + preemption machinery is cache-layout agnostic:
    a full mixed workload serves under kv_quant=int8 and outputs equal
    single-request generation (batching invariance holds quantized)."""
    cfg, params = _setup(overrides=["inference.kv_quant=int8"])
    eng = InferenceEngine(cfg, params)
    prompts = [[5, 3, 9], [250, 17], [1, 2, 3, 4, 5, 6, 7]]
    batched = eng.generate(prompts, 6)
    for p, want in zip(prompts, batched):
        solo = InferenceEngine(cfg, params).generate([p], 6)[0]
        assert solo == want


def test_kv_int8_rejects_large_pages():
    """One lane tile holds one page's scales: page_size > 128 must raise
    clearly at engine construction, not fail inside the kernel."""
    cfg, params = _setup(overrides=["inference.kv_quant=int8",
                                    "inference.max_seq_len=512",
                                    "inference.page_size=256",
                                    "inference.prefill_chunk=256"])
    with pytest.raises(ValueError, match="page_size"):
        InferenceEngine(cfg, params)


def test_step_timing_accounting_sums():
    """The device/host step-time split must account for the measured wall
    time: device_s + host_s == sum of step() durations (to timer noise),
    windows counts only decoding steps, and reset zeroes it."""
    import time as _time

    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    eng.submit([5, 3, 9], 6)
    t0 = _time.perf_counter()
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
    wall = _time.perf_counter() - t0
    t = eng.reset_timing()
    assert t["steps"] == steps
    assert 0 < t["windows"] <= steps
    assert t["device_s"] > 0 and t["host_s"] > 0
    assert t["prefill_s"] > 0               # admission burst, own bucket
    total = t["device_s"] + t["host_s"] + t["prefill_s"]
    # The split partitions each step's wall time exactly; across steps it
    # must match the loop's wall clock minus inter-step Python overhead.
    assert total <= wall
    assert total > 0.5 * wall
    # Idle step (no work): counts a step, no window, negligible device.
    eng.step()
    t2 = eng.reset_timing()
    assert t2["steps"] == 1 and t2["windows"] == 0
    assert t2["device_s"] == 0.0


def test_preemption_under_pool_pressure():
    """When concurrent decodes exhaust the page pool, the youngest request
    is preempted, re-prefilled from its context later, and still produces
    exactly the single-request tokens."""
    cfg, params = _setup(overrides=["inference.num_pages=8"])
    # 7 usable pages of 16 tokens; two requests decoding from 15-token
    # prompts out to 15+50=65 tokens each want 5 pages apiece at the end —
    # more than the pool — so at least one preemption must happen.
    prompts = [[5, 3, 9, 250, 17, 8, 100, 42, 77, 31, 2, 6, 90, 55, 21],
               [7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53, 59, 61]]
    singles = [
        InferenceEngine(cfg, params).generate([p], 50)[0] for p in prompts
    ]
    eng = InferenceEngine(cfg, params)
    batched = eng.generate(prompts, 50)
    assert eng.preemptions > 0, "scenario failed to exercise preemption"
    assert batched == singles


def test_max_new_tokens_zero_is_prefill_only():
    cfg, params = _setup()
    assert InferenceEngine(cfg, params).generate([[1, 2, 3]], 0) == [[]]


@pytest.mark.slow
def test_long_generation_allocates_pages_on_demand():
    """Crossing page boundaries mid-decode allocates new pages and keeps
    matching the reference.

    slow (tier-1 budget, round 8): the 20-token reference forward makes
    this the single heaviest infer test (~37s CPU); page-on-demand growth
    stays pinned in tier-1 by the spec-decode rollback suite
    (test_spec_decode.test_rollback_state_exact walks the page footprint
    every step)."""
    cfg, params = _setup()
    prompt = [5, 3, 9, 250, 17, 8, 100, 42, 77, 31, 2, 6, 90, 55, 21]  # 15
    n = 20  # crosses the 16-token page boundary twice
    ref = _ref_generate(params, cfg.model, prompt, n)
    out = InferenceEngine(cfg, params).generate([prompt], n)[0]
    assert out == ref


# -- sampling ---------------------------------------------------------------


def test_sample_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 5.0]])
    toks = sample(logits, jax.random.key(0), temperature=0.0)
    assert toks.tolist() == [1, 2]


def test_sample_top_k_restricts_support():
    logits = jnp.asarray([[5.0, 4.0, -10.0, -10.0]])
    for s in range(20):
        t = sample(logits, jax.random.key(s), temperature=1.0, top_k=2)
        assert int(t[0]) in (0, 1)


def test_sample_top_p_restricts_support():
    logits = jnp.asarray([[10.0, 9.0, -10.0, -10.0]])
    for s in range(20):
        t = sample(logits, jax.random.key(s), temperature=1.0, top_p=0.9)
        assert int(t[0]) in (0, 1)


# slow (tier-1 budget, round 8): cumulative admission headroom is
# also exercised in tier-1 by test_chunked_prefill's mid-prompt
# preemption scenario and test_spec_decode's rollback-footprint walk.
@pytest.mark.slow
def test_admission_burst_reserves_decode_headroom():
    """A multi-request admission burst must account for every admitted
    request's first-decode-window headroom cumulatively: over-committing let
    _grow_pages preempt the OLDEST request in the very step it prefilled
    (discarding its work). With the reservation, the second request simply
    waits and nobody is preempted."""
    cfg, params = _setup(overrides=[
        "inference.num_pages=8",        # 7 usable; first_window=5 per req
        "inference.decode_window=64",
        "inference.max_new_tokens=8",
    ])
    eng = InferenceEngine(cfg, params)
    prompts = [[(i * 7 + j) % 250 + 1 for j in range(16)] for i in range(2)]
    refs = [_ref_generate(params, cfg.model, p, 8) for p in prompts]
    outs = eng.generate(prompts, 8)
    assert outs == refs
    assert eng.preemptions == 0, (
        f"admission burst over-committed the pool ({eng.preemptions} "
        "preemptions)"
    )


def test_stream_matches_generate():
    """stream() yields exactly generate()'s tokens, incrementally, in
    window-sized chunks, ending each request exactly once."""
    cfg, params = _setup(overrides=["inference.decode_window=2"])
    prompts = [[5, 3, 9, 250, 17], [7, 7, 2]]
    want = InferenceEngine(cfg, params).generate(prompts, 8)

    eng = InferenceEngine(cfg, params)
    got: dict[int, list[int]] = {}
    chunks = 0
    for rid, toks in eng.stream(prompts, 8):
        assert toks, "empty yield"
        got.setdefault(rid, []).extend(toks)
        chunks += 1
    rids = sorted(got)
    assert [got[r] for r in rids] == want
    assert chunks > len(prompts)  # incremental, not one-shot


def test_stream_zero_token_requests_still_announced():
    """max_new_tokens=0 (scoring) requests yield exactly one empty chunk so
    consumers can realign outputs with prompts."""
    cfg, params = _setup()
    eng = InferenceEngine(cfg, params)
    events = list(eng.stream([[5, 3], [7, 1, 2]], 0))
    assert sorted(r for r, _ in events) == sorted(set(r for r, _ in events))
    assert len(events) == 2
    assert all(toks == [] for _, toks in events)


def test_per_request_sampling_params():
    """Per-request sampling (vLLM-style): a greedy request batched with a
    hot-temperature request still reproduces its single-request greedy
    tokens; the sampled request draws different, valid tokens."""
    cfg, params = _setup()  # config default temperature=0 (greedy)
    p_greedy, p_hot = [5, 3, 9, 250, 17], [7, 11, 2]
    ref = InferenceEngine(cfg, params).generate([p_greedy], 8)[0]

    eng = InferenceEngine(cfg, params)
    eng.submit(p_greedy, 8)
    eng.submit(p_hot, 8, temperature=1.0, top_k=50)
    done = []
    while eng.has_work():
        done += eng.step()
    by_rid = sorted(done, key=lambda r: r.rid)
    assert by_rid[0].generated == ref
    hot = by_rid[1].generated
    assert len(hot) == 8
    assert all(0 <= t < cfg.model.vocab_size for t in hot)


def test_sample_per_row_matches_scalar():
    """The vectorized per-row sampler equals the scalar path row-wise."""
    import jax.numpy as jnp

    key = jax.random.key(0)
    logits = jax.random.normal(jax.random.key(1), (4, 64)) * 3
    for kwargs in [
        dict(temperature=0.0, top_k=0, top_p=1.0),
        dict(temperature=0.7, top_k=5, top_p=1.0),
        dict(temperature=1.3, top_k=0, top_p=0.8),
        dict(temperature=0.9, top_k=7, top_p=0.6),
    ]:
        a = sample(logits, key, **kwargs)
        b = sample(
            logits, key,
            temperature=jnp.full(4, kwargs["temperature"]),
            top_k=jnp.full(4, kwargs["top_k"], jnp.int32),
            top_p=jnp.full(4, kwargs["top_p"]),
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)), kwargs


def test_sample_mixed_rows():
    """Greedy rows in a mixed batch are exactly argmax."""
    import jax.numpy as jnp

    logits = jax.random.normal(jax.random.key(2), (3, 32))
    toks = sample(
        logits, jax.random.key(3),
        temperature=jnp.asarray([0.0, 1.0, 0.0]),
        top_k=jnp.asarray([0, 10, 0], jnp.int32),
        top_p=jnp.asarray([1.0, 0.9, 1.0]),
    )
    am = np.argmax(np.asarray(logits), axis=-1)
    assert int(toks[0]) == am[0] and int(toks[2]) == am[2]


@pytest.mark.parametrize("kernels", [
    "xla",
    # slow (tier-1 budget, round 8): the interpret-mode run costs ~25s
    # CPU; the pallas SWA path stays pinned in tier-1 by the sharded
    # gemma2-beyond-window tests.
    pytest.param("pallas_interpret", marks=pytest.mark.slow),
])
def test_sliding_window_engine_matches_forward(kernels):
    """Windowed serving (prefill + paged decode, both kernel paths) must
    reproduce greedy generation from the windowed training forward —
    the training/serving-semantics equivalence SWA makes easy to break."""
    cfg, params = _setup(overrides=[
        "model.sliding_window=6", f"model.kernels={kernels}",
    ])
    prompt = [5, 3, 9, 250, 17, 8, 100, 42, 77]   # context > window
    ref = _ref_generate(params, cfg.model, prompt, 10)
    out = InferenceEngine(cfg, params).generate([prompt], 10)[0]
    assert out == ref


@pytest.mark.slow  # 90-token SWA generation, ~80s on the CPU tier
def test_rolling_window_bounds_page_footprint():
    """SWA serving is O(window) in pages: a pool too small for the full
    context (old behavior: single-request MemoryError) serves a long
    windowed generation correctly because dead pages are never allocated
    at admission and roll back to the pool as the window advances."""
    cfg, params = _setup(overrides=[
        "model.sliding_window=20",
        "inference.num_pages=6",         # 5 usable < 7 full-context pages
        "inference.max_new_tokens=90",
    ])
    prompt = [(i * 13) % 250 + 1 for i in range(10)]
    ref = _ref_generate(params, cfg.model, prompt, 90)

    eng = InferenceEngine(cfg, params)
    out = eng.generate([prompt], 90)[0]
    assert out == ref
    assert eng.preemptions == 0
    # All pages returned after completion.
    assert eng.alloc.free_pages == cfg.inference.num_pages - 1


def test_windowed_submit_accounts_for_bucket_bottom_peak():
    """The singleton-footprint check must use the WORST context (a
    prefill-bucket bottom), not max_context: a request accepted by submit
    but unadmittable would hang generate() forever."""
    cfg, params = _setup(overrides=[
        "model.sliding_window=4096",
        "inference.max_seq_len=8192", "inference.page_size=64",
        "inference.prefill_chunk=512", "inference.num_pages=72",
        "inference.max_batch_size=2",
    ])
    eng = InferenceEngine(cfg, params)
    prompt = [1] * 5633
    # Worst re-prefill (bucket 6144 -> 96 logical pages, only 24 dead)
    # needs ~73 real pages > 71 usable: must reject at submit, not hang.
    with pytest.raises(ValueError, match="pages"):
        eng.submit(prompt, 500)
    # A big enough pool accepts the same request.
    cfg2, _ = _setup(overrides=[
        "model.sliding_window=4096",
        "inference.max_seq_len=8192", "inference.page_size=64",
        "inference.prefill_chunk=512", "inference.num_pages=80",
        "inference.max_batch_size=2",
    ])
    InferenceEngine(cfg2, params).submit(prompt, 500)
