"""Unit tier: Pallas kernels vs jnp/XLA reference implementations.

SURVEY.md §5: kernels run through the Pallas interpreter on CPU so the same
code paths are exercised without a TPU; fwd and grads must match the xla ops
to fp32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from orion_tpu.ops.attention import attention_xla
from orion_tpu.ops.norms import _rmsnorm_xla
from orion_tpu.ops.pallas import flash_attention, rmsnorm_pallas, rope_pallas
from orion_tpu.ops.rope import _rope_xla


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype=dtype)


def _qkv(B=2, Sq=64, Skv=64, N=4, K=4, H=32, dtype=jnp.float32):
    return (
        _rand(0, B, Sq, N, H, dtype=dtype),
        _rand(1, B, Skv, K, H, dtype=dtype),
        _rand(2, B, Skv, K, H, dtype=dtype),
    )


class TestFlashAttention:
    def test_causal_fwd(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = attention_xla(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_non_causal_fwd(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=False, interpret=True)
        ref = attention_xla(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_gqa(self):
        q, k, v = _qkv(N=8, K=2)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_xla(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_multiple_kv_blocks(self):
        # Sequence longer than one block forces the online-softmax carry.
        q, k, v = _qkv(Sq=160, Skv=160)
        out = flash_attention(q, k, v, block_q=64, block_kv=64, interpret=True)
        ref = attention_xla(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_ragged_padding(self):
        # Non-multiple-of-block lengths exercise the padding mask.
        q, k, v = _qkv(Sq=100, Skv=100)
        out = flash_attention(q, k, v, block_q=64, block_kv=64, interpret=True)
        ref = attention_xla(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_segment_ids(self):
        q, k, v = _qkv()
        seg = jnp.concatenate(
            [jnp.zeros((2, 32), jnp.int32), jnp.ones((2, 32), jnp.int32)], axis=1
        )
        out = flash_attention(
            q, k, v, q_segment_ids=seg, kv_segment_ids=seg, interpret=True
        )
        ref = attention_xla(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_softcap(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, logit_softcap=20.0, interpret=True)
        ref = attention_xla(q, k, v, logit_softcap=20.0)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_q_offset_decode(self):
        # Decode-style: 8 new queries attending into a longer kv history.
        q, k, v = _qkv(Sq=8, Skv=72)
        out = flash_attention(q, k, v, q_offset=64, interpret=True)
        ref = attention_xla(q, k, v, q_offset=64)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("case", ["mha", "gqa", "softcap", "ragged"])
    def test_grads_match_xla(self, case):
        kw = {}
        if case == "gqa":
            q, k, v = _qkv(N=8, K=2)
        elif case == "softcap":
            q, k, v = _qkv()
            kw["logit_softcap"] = 20.0
        elif case == "ragged":
            q, k, v = _qkv(Sq=100, Skv=100)
        else:
            q, k, v = _qkv()

        def loss_pallas(q, k, v):
            o = flash_attention(
                q, k, v, interpret=True, block_q=64, block_kv=64, **kw
            )
            return jnp.sum(o * o)

        def loss_xla(q, k, v):
            o = attention_xla(q, k, v, **kw)
            return jnp.sum(o * o)

        gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gp, gx, "qkv"):
            np.testing.assert_allclose(
                a, b, rtol=2e-4, atol=2e-4, err_msg=f"d{name} mismatch"
            )

    def test_grads_segment_ids(self):
        q, k, v = _qkv()
        seg = jnp.concatenate(
            [jnp.zeros((2, 32), jnp.int32), jnp.ones((2, 32), jnp.int32)], axis=1
        )

        def lp(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, q_segment_ids=seg, kv_segment_ids=seg, interpret=True
                ) ** 2
            )

        def lx(q, k, v):
            return jnp.sum(
                attention_xla(q, k, v, q_segment_ids=seg, kv_segment_ids=seg) ** 2
            )

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(lx, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    def test_explicit_positions_match_permuted_reference(self):
        """Position-based causal masking (striped/permuted layouts): flash
        on a permuted sequence with explicit positions equals the natural-
        order reference with rows/cols permuted, fwd and grads."""
        B, S, N, K, H = 2, 64, 4, 2, 32
        q, kk, v = _qkv(B=B, Sq=S, Skv=S, N=N, K=K, H=H)
        perm = jax.random.permutation(jax.random.key(7), S)
        pos = jnp.broadcast_to(perm[None], (B, S))

        qp, kp, vp = q[:, perm], kk[:, perm], v[:, perm]

        def loss_p(qp, kp, vp):
            out = flash_attention(
                qp, kp, vp, causal=True, interpret=True,
                q_positions=pos, kv_positions=pos,
            )
            return jnp.sum(out ** 2), out

        def loss_r(q, kk, v):
            out = attention_xla(q, kk, v, causal=True)
            return jnp.sum(out[:, perm] ** 2), out

        (_, out_p), g_p = jax.value_and_grad(
            loss_p, argnums=(0, 1, 2), has_aux=True)(qp, kp, vp)
        (_, out_r), g_r = jax.value_and_grad(
            loss_r, argnums=(0, 1, 2), has_aux=True)(q, kk, v)
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(out_r[:, perm]),
            rtol=1e-5, atol=1e-5,
        )
        for a, b in zip(g_p, g_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b[:, perm]), rtol=1e-4, atol=1e-4
            )

    def test_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_xla(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32), rtol=2e-2, atol=2e-2
        )


class TestRMSNorm:
    def test_fwd(self):
        x = _rand(0, 4, 96, 128)
        s = _rand(1, 128) * 0.1 + 1.0
        out = rmsnorm_pallas(x, s, eps=1e-5, interpret=True)
        ref = _rmsnorm_xla(x, s, 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_fwd_ragged_rows(self):
        x = _rand(0, 3, 37, 64)
        s = _rand(1, 64)
        out = rmsnorm_pallas(x, s, eps=1e-6, interpret=True, block_rows=32)
        ref = _rmsnorm_xla(x, s, 1e-6)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_grads(self):
        x = _rand(0, 2, 24, 64)
        s = _rand(1, 64) * 0.1 + 1.0

        def lp(x, s):
            return jnp.sum(rmsnorm_pallas(x, s, eps=1e-5, interpret=True) ** 2)

        def lx(x, s):
            return jnp.sum(_rmsnorm_xla(x, s, 1e-5) ** 2)

        gp = jax.grad(lp, argnums=(0, 1))(x, s)
        gx = jax.grad(lx, argnums=(0, 1))(x, s)
        np.testing.assert_allclose(gp[0], gx[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gp[1], gx[1], rtol=1e-4, atol=1e-4)


class TestRoPE:
    def test_fwd(self):
        x = _rand(0, 2, 48, 4, 32)
        pos = jnp.broadcast_to(jnp.arange(48)[None, :], (2, 48))
        out = rope_pallas(x, pos, theta=10_000.0, interpret=True)
        ref = _rope_xla(x, pos, 10_000.0)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_fwd_1d_positions_and_offset(self):
        # Decode: positions far from zero.
        x = _rand(0, 2, 8, 4, 32)
        pos = jnp.arange(1000, 1008)
        out = rope_pallas(x, pos, theta=500_000.0, interpret=True)
        ref = _rope_xla(x, pos, 500_000.0)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_grads(self):
        x = _rand(0, 1, 16, 2, 16)
        pos = jnp.arange(16)[None, :]

        def lp(x):
            return jnp.sum(rope_pallas(x, pos, theta=10_000.0, interpret=True) ** 2)

        def lx(x):
            return jnp.sum(_rope_xla(x, pos, 10_000.0) ** 2)

        gp = jax.grad(lp)(x)
        gx = jax.grad(lx)(x)
        np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=1e-4)


class TestModelWithPallasKernels:
    def test_forward_matches_xla_kernels(self):
        """Whole-model parity: tiny llama with kernels=pallas_interpret."""
        from orion_tpu.config import get_config
        from orion_tpu.models import forward, init_params

        cfg = get_config("tiny-llama", ["model.dtype=float32"]).model
        params = init_params(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab_size)

        logits_xla, _ = forward(params, tokens, cfg)
        import dataclasses

        cfg_p = dataclasses.replace(cfg, kernels="pallas_interpret")
        logits_pallas, _ = forward(params, tokens, cfg_p)
        np.testing.assert_allclose(
            logits_pallas, logits_xla, rtol=5e-4, atol=5e-4
        )


# -- paged decode attention -------------------------------------------------


def _paged_reference(q, k_pool, v_pool, page_table, last_pos):
    from orion_tpu.ops.attention import attention_xla

    B, N, H = q.shape
    P = page_table.shape[1]
    K, psz = k_pool.shape[1], k_pool.shape[2]
    # Pool pages are [K, psz, H] (kv_cache.py layout).
    k_ctx = k_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    v_ctx = v_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    mask = (
        jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
        <= last_pos[:, None, None]
    )
    return attention_xla(
        q[:, None], k_ctx, v_ctx, causal=False, mask=mask
    )[:, 0]


@pytest.mark.parametrize("gqa", [(8, 8), (8, 2), (4, 1)])
def test_paged_attention_matches_gather(gqa):
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    N, K = gqa
    B, H, psz, P, num_pages = 3, 64, 16, 4, 32
    keys = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(keys[0], (B, N, H), jnp.float32)
    k_pool = jax.random.normal(keys[1], (num_pages, K, psz, H), jnp.float32)
    v_pool = jax.random.normal(keys[2], (num_pages, K, psz, H), jnp.float32)
    # Shuffled non-contiguous page assignment, ragged lengths.
    page_table = jnp.asarray(
        [[5, 17, 2, 9], [30, 1, 7, 3], [11, 4, 0, 22]], jnp.int32
    )
    last_pos = jnp.asarray([0, 37, 63], jnp.int32)  # 1, 38, 64 valid tokens

    ref = _paged_reference(q, k_pool, v_pool, page_table, last_pos)
    out = paged_attention(
        q, k_pool, v_pool, page_table, last_pos, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_attention_fused_write():
    """The in-kernel KV write (input/output-aliased pool) must equal an
    external scatter followed by attention."""
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    N, K = 8, 2
    B, H, psz, P, num_pages = 3, 64, 16, 4, 32
    keys = jax.random.split(jax.random.key(3), 6)
    q = jax.random.normal(keys[0], (B, N, H), jnp.float32)
    k_pool = jax.random.normal(keys[1], (num_pages, K, psz, H), jnp.float32)
    v_pool = jax.random.normal(keys[2], (num_pages, K, psz, H), jnp.float32)
    k_new = jax.random.normal(keys[3], (B, K, H), jnp.float32)
    v_new = jax.random.normal(keys[4], (B, K, H), jnp.float32)
    page_table = jnp.asarray(
        [[5, 17, 2, 9], [30, 1, 7, 3], [11, 4, 0, 22]], jnp.int32
    )
    last_pos = jnp.asarray([0, 37, 63], jnp.int32)  # the position written

    # Reference: scatter externally, then attend.
    rows = page_table[jnp.arange(B), last_pos // psz]
    kp_ref = k_pool.at[rows, :, last_pos % psz].set(k_new)
    vp_ref = v_pool.at[rows, :, last_pos % psz].set(v_new)
    ref = _paged_reference(q, kp_ref, vp_ref, page_table, last_pos)

    out, kp, vp = paged_attention(
        q, k_pool, v_pool, page_table, last_pos,
        k_new=k_new, v_new=v_new, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(kp[rows, :, last_pos % psz]), np.asarray(k_new), atol=0
    )
    np.testing.assert_allclose(
        np.asarray(vp[rows, :, last_pos % psz]), np.asarray(v_new), atol=0
    )


def test_flash_ragged_padding_rows_parity_and_grads():
    """Segment id 0 marks padding (ragged prefill / packed tails): the
    all-padding block SKIP must not change results — parity vs the xla
    reference with the same segment mask, fwd and grads, at per-row
    ragged lengths that leave whole blocks padded."""
    from orion_tpu.ops.attention import attention_xla
    from orion_tpu.ops.pallas.flash_attention import flash_attention

    B, S, N, K, H = 3, 256, 4, 2, 64
    ks = jax.random.split(jax.random.key(17), 3)
    q = jax.random.normal(ks[0], (B, S, N, H), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, K, H), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, K, H), jnp.float32)
    lengths = jnp.asarray([256, 70, 3])      # full, mid-block, tiny
    seg = (jnp.arange(S)[None, :] < lengths[:, None]).astype(jnp.int32)

    def loss_p(q, k, v):
        o = flash_attention(q, k, v, causal=True, q_segment_ids=seg,
                            kv_segment_ids=seg, seg_pad_zero=True,
                            block_q=64, block_kv=64, interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2 * seg[..., None, None])

    def loss_x(q, k, v):
        o = attention_xla(q, k, v, causal=True, q_segment_ids=seg,
                          kv_segment_ids=seg)
        return jnp.sum(o.astype(jnp.float32) ** 2 * seg[..., None, None])

    o_p = flash_attention(q, k, v, causal=True, q_segment_ids=seg,
                          kv_segment_ids=seg, seg_pad_zero=True,
                          block_q=64, block_kv=64, interpret=True)
    o_x = attention_xla(q, k, v, causal=True, q_segment_ids=seg,
                        kv_segment_ids=seg)
    # Compare only real rows: padding rows are garbage by contract.
    m = np.asarray(seg, bool)
    np.testing.assert_allclose(
        np.asarray(o_p)[m], np.asarray(o_x)[m], atol=2e-5)
    g_p = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    g_x = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_x, g_p):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-4)


def test_paged_attention_int8_matches_dequantized_reference():
    """int8 pools + per-(token, head) scales: the kernel's in-place
    dequantization (K scales on logit columns, V scales folded into the
    probabilities) must reproduce masked attention over the explicitly
    dequantized pools, including the fused in-kernel quantized write."""
    from orion_tpu.infer.kv_cache import quantize_kv
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    N, K = 8, 2
    B, H, psz, P, num_pages = 3, 64, 16, 4, 32
    SW = 128
    keys = jax.random.split(jax.random.key(11), 6)
    q = jax.random.normal(keys[0], (B, N, H), jnp.float32)
    kf = jax.random.normal(keys[1], (num_pages, K, psz, H), jnp.float32)
    vf = jax.random.normal(keys[2], (num_pages, K, psz, H), jnp.float32)
    k_new = jax.random.normal(keys[3], (B, K, H), jnp.float32)
    v_new = jax.random.normal(keys[4], (B, K, H), jnp.float32)
    page_table = jnp.asarray(
        [[5, 17, 2, 9], [30, 1, 7, 3], [11, 4, 0, 22]], jnp.int32
    )
    last_pos = jnp.asarray([0, 37, 63], jnp.int32)

    # Host-side quantization (the prefill path): [rows, K, psz, H] over H.
    kq, ks = quantize_kv(kf.transpose(0, 2, 1, 3))   # scale [rows, psz, K]
    vq, vs = quantize_kv(vf.transpose(0, 2, 1, 3))
    kq = kq.transpose(0, 2, 1, 3)
    vq = vq.transpose(0, 2, 1, 3)
    k_scale = jnp.zeros((num_pages, K, SW), jnp.float32
                        ).at[:, :, :psz].set(ks.transpose(0, 2, 1))
    v_scale = jnp.zeros((num_pages, K, SW), jnp.float32
                        ).at[:, :, :psz].set(vs.transpose(0, 2, 1))

    # Reference: dequantize everything ([rows, K, psz] scales broadcast
    # over H), external scatter, masked attention.
    kd = kq.astype(jnp.float32) * k_scale[:, :, :psz][..., None]
    vd = vq.astype(jnp.float32) * v_scale[:, :, :psz][..., None]
    knq, kns = quantize_kv(k_new)
    vnq, vns = quantize_kv(v_new)
    rows = page_table[jnp.arange(B), last_pos // psz]
    kd_ref = kd.at[rows, :, last_pos % psz].set(
        knq.astype(jnp.float32) * kns[..., None])
    vd_ref = vd.at[rows, :, last_pos % psz].set(
        vnq.astype(jnp.float32) * vns[..., None])
    ref = _paged_reference(q, kd_ref, vd_ref, page_table, last_pos)

    out, kp2, vp2, ks2, vs2 = paged_attention(
        q, kq, vq, page_table, last_pos,
        k_new=k_new, v_new=v_new,
        k_scale=k_scale, v_scale=v_scale, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # In-kernel quantized write matches the host-side quantization.
    np.testing.assert_allclose(
        np.asarray(kp2[rows, :, last_pos % psz]), np.asarray(knq), atol=0)
    np.testing.assert_allclose(
        np.asarray(ks2[rows, :, last_pos % psz]), np.asarray(kns),
        rtol=1e-6)
    # And the quantized attention is close to the float answer.
    float_ref = _paged_reference(
        q, kf.at[rows, :, last_pos % psz].set(k_new),
        vf.at[rows, :, last_pos % psz].set(v_new), page_table, last_pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(float_ref), atol=0.06)


def test_paged_attention_softcap():
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    B, N, K, H, psz, P, num_pages = 2, 4, 2, 32, 8, 3, 16
    keys = jax.random.split(jax.random.key(1), 4)
    q = jax.random.normal(keys[0], (B, N, H), jnp.float32) * 4
    k_pool = jax.random.normal(keys[1], (num_pages, K, psz, H), jnp.float32)
    v_pool = jax.random.normal(keys[2], (num_pages, K, psz, H), jnp.float32)
    page_table = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    last_pos = jnp.asarray([10, 20], jnp.int32)

    from orion_tpu.ops.attention import attention_xla

    k_ctx = k_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    v_ctx = v_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    mask = (
        jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
        <= last_pos[:, None, None]
    )
    ref = attention_xla(
        q[:, None], k_ctx, v_ctx, causal=False, mask=mask, logit_softcap=20.0
    )[:, 0]
    out = paged_attention(
        q, k_pool, v_pool, page_table, last_pos,
        logit_softcap=20.0, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestSlidingWindow:
    """Sliding-window attention (Mistral-family): flash kernel vs xla vs a
    hand-built mask, fwd + grads, across block boundaries."""

    def _ref(self, q, k, v, window, seg=None):
        # Independent reference: explicit boolean mask, not attention_mask.
        Sq, Skv = q.shape[1], k.shape[1]
        d = jnp.arange(Sq)[:, None] - jnp.arange(Skv)[None, :]
        mask = (d >= 0) & (d < window)
        if seg is not None:
            mask = mask[None] & (seg[:, :, None] == seg[:, None, :])
        return attention_xla(q, k, v, causal=False, mask=mask)

    def test_xla_window_matches_manual_mask(self):
        q, k, v = _qkv(Sq=96, Skv=96)
        out = attention_xla(q, k, v, causal=True, window=17)
        np.testing.assert_allclose(
            out, self._ref(q, k, v, 17), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.parametrize("window", [8, 64, 80, 1000])
    def test_flash_window_matches_xla(self, window):
        # Window smaller / equal / larger than the 64-wide blocks: the
        # behind-the-window block skip must never drop visible columns.
        q, k, v = _qkv(Sq=192, Skv=192)
        out = flash_attention(
            q, k, v, window=window, block_q=64, block_kv=64, interpret=True
        )
        np.testing.assert_allclose(
            out, self._ref(q, k, v, window), rtol=1e-5, atol=1e-5
        )

    def test_flash_window_with_segments(self):
        q, k, v = _qkv(Sq=96, Skv=96)
        seg = jnp.asarray(
            np.repeat([[1, 2, 3]], 2, 0).repeat(32, 1), jnp.int32
        )
        out = flash_attention(
            q, k, v, window=10, q_segment_ids=seg, kv_segment_ids=seg,
            block_q=32, block_kv=32, interpret=True,
        )
        np.testing.assert_allclose(
            out, self._ref(q, k, v, 10, seg), rtol=1e-5, atol=1e-5
        )

    def test_flash_window_grads_match_xla(self):
        q, k, v = _qkv(Sq=128, Skv=128)

        def loss_flash(q, k, v):
            return flash_attention(
                q, k, v, window=24, block_q=64, block_kv=64, interpret=True
            ).sum()

        def loss_xla(q, k, v):
            return attention_xla(q, k, v, causal=True, window=24).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gx):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)

    def test_flash_window_explicit_positions(self):
        # Permuted layout: positions carried explicitly; window distance
        # must follow positions, not indices.
        q, k, v = _qkv(Sq=64, Skv=64)
        perm = np.asarray(np.random.default_rng(0).permutation(64))
        pos = jnp.asarray(perm, jnp.int32)
        out = flash_attention(
            q, k, v, window=9, q_positions=pos, kv_positions=pos,
            block_q=32, block_kv=32, interpret=True,
        )
        # Reference: unpermute, run index-based, re-permute.
        inv = np.argsort(perm)
        ref_sorted = self._ref(q[:, inv], k[:, inv], v[:, inv], 9)
        np.testing.assert_allclose(
            out, ref_sorted[:, perm], rtol=1e-5, atol=1e-5
        )

    def test_window_requires_causal(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=4, interpret=True)
        with pytest.raises(ValueError, match="causal"):
            attention_xla(q, k, v, causal=False, window=4)

    def test_model_level_sliding_window(self):
        """End-to-end: a model with sliding_window trains and differs from
        full attention exactly when context exceeds the window."""
        from orion_tpu.config import get_config
        from orion_tpu.models import forward, init_params

        cfg_full = get_config("tiny-llama").model
        cfg_win = get_config("tiny-llama", ["model.sliding_window=4"]).model
        params = init_params(cfg_full, jax.random.key(0))
        tokens = jax.random.randint(
            jax.random.key(1), (1, 16), 0, cfg_full.vocab_size
        )
        lf, _ = forward(params, tokens, cfg_full)
        lw, _ = forward(params, tokens, cfg_win)
        # First window tokens see identical context; later ones don't.
        np.testing.assert_allclose(
            np.asarray(lf[:, :4]), np.asarray(lw[:, :4]), atol=1e-5
        )
        assert not np.allclose(np.asarray(lf[:, 8:]), np.asarray(lw[:, 8:]))


@pytest.mark.parametrize("window", [5, 16, 40, 1000])
def test_paged_attention_sliding_window(window):
    """Windowed paged decode: pages behind the window are skipped (their
    DMAs clamp to the window's first page) yet the result equals the
    masked gather reference."""
    from orion_tpu.ops.attention import attention_xla
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    N, K = 8, 2
    B, H, psz, P, num_pages = 3, 64, 16, 4, 32
    keys = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(keys[0], (B, N, H), jnp.float32)
    k_pool = jax.random.normal(keys[1], (num_pages, K, psz, H), jnp.float32)
    v_pool = jax.random.normal(keys[2], (num_pages, K, psz, H), jnp.float32)
    page_table = jnp.asarray(
        [[5, 17, 2, 9], [30, 1, 7, 3], [11, 4, 0, 22]], jnp.int32
    )
    last_pos = jnp.asarray([0, 37, 63], jnp.int32)

    k_ctx = k_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    v_ctx = v_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    pos = jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
    mask = (pos <= last_pos[:, None, None]) & (
        pos >= (last_pos - window + 1)[:, None, None]
    )
    ref = attention_xla(q[:, None], k_ctx, v_ctx, causal=False, mask=mask)[
        :, 0
    ]
    out = paged_attention(
        q, k_pool, v_pool, page_table, last_pos, window=window,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_paged_attention_rejects_degenerate_window():
    from orion_tpu.ops.pallas.paged_attention import paged_attention

    q = jnp.zeros((1, 4, 64))
    pool = jnp.zeros((4, 2, 16, 64))
    with pytest.raises(ValueError, match="window"):
        paged_attention(
            q, pool, pool, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros(1, jnp.int32), window=0, interpret=True,
        )


# -- multi-query ragged paged attention (speculative verification) ----------


def _ragged_reference(q, k_pool, v_pool, page_table, start, lens,
                      k_new=None, v_new=None, window=None, softcap=None):
    """runner._verify_layer's xla semantics: scatter all real tokens
    (padding tokens park on a dummy extra row — the engine's scratch page
    stand-in, since these tests use page 0 as a real page), gather the
    padded context, mask per query (own position + earlier same-dispatch
    drafts; optional sliding window)."""
    from orion_tpu.ops.attention import attention_xla

    B, W, N, H = q.shape
    K, psz = k_pool.shape[1], k_pool.shape[2]
    P = page_table.shape[1]
    npg = k_pool.shape[0]
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    q_pos = start[:, None] + steps                         # [B, W]
    if k_new is not None:
        valid = steps < lens[:, None]
        k_pool = jnp.concatenate(
            [k_pool, jnp.zeros((1,) + k_pool.shape[1:], k_pool.dtype)])
        v_pool = jnp.concatenate(
            [v_pool, jnp.zeros((1,) + v_pool.shape[1:], v_pool.dtype)])
        rows = jnp.where(
            valid, page_table[jnp.arange(B)[:, None], q_pos // psz], npg
        )
        off = q_pos % psz
        k_pool = k_pool.at[rows, :, off].set(k_new)[:npg]
        v_pool = v_pool.at[rows, :, off].set(v_new)[:npg]
    k_ctx = k_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    v_ctx = v_pool[page_table].transpose(0, 1, 3, 2, 4).reshape(
        B, P * psz, K, H)
    kv = jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
    mask = kv <= q_pos[:, :, None]
    if window is not None:
        mask &= kv >= (q_pos - window + 1)[:, :, None]
    out = attention_xla(
        q, k_ctx, v_ctx, causal=False, mask=mask, logit_softcap=softcap
    )
    return out, k_pool, v_pool


def _ragged_case(key=2, W=5, N=8, K=2):
    B, H, psz, num_pages = 3, 64, 16, 32
    ks = jax.random.split(jax.random.key(key), 6)
    q = jax.random.normal(ks[0], (B, W, N, H), jnp.float32)
    k_pool = jax.random.normal(ks[1], (num_pages, K, psz, H), jnp.float32)
    v_pool = jax.random.normal(ks[2], (num_pages, K, psz, H), jnp.float32)
    k_new = jax.random.normal(ks[3], (B, W, K, H), jnp.float32)
    v_new = jax.random.normal(ks[4], (B, W, K, H), jnp.float32)
    page_table = jnp.asarray(
        [[5, 17, 2, 9], [30, 1, 7, 3], [11, 4, 0, 22]], jnp.int32
    )
    # Full-width from zero / single mid-page / ragged near the table end.
    start = jnp.asarray([0, 13, 59], jnp.int32)
    lens = jnp.asarray([W, 1, 3], jnp.int32)
    return q, k_pool, v_pool, k_new, v_new, page_table, start, lens


def _assert_real_rows_close(got, want, lens, atol=2e-5):
    got, want = np.asarray(got), np.asarray(want)
    for b in range(len(lens)):
        w = int(lens[b])
        np.testing.assert_allclose(got[b, :w], want[b, :w], atol=atol)


@pytest.mark.parametrize("gqa", [(8, 8), (8, 2), (4, 1)])
def test_ragged_paged_attention_matches_gather(gqa):
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    N, K = gqa
    q, kp, vp, _, _, pt, start, lens = _ragged_case(N=N, K=K)
    ref, _, _ = _ragged_reference(q, kp, vp, pt, start, lens)
    out = ragged_paged_attention(q, kp, vp, pt, start, lens, interpret=True)
    _assert_real_rows_close(out, ref, lens)


def test_ragged_paged_attention_fused_write():
    """In-kernel multi-token KV write == external scatter + attention:
    outputs match and the written pools are BITWISE equal (padding tokens
    and clamped tail revisits leave every unwritten position untouched).
    The causal structure among the W new positions rides the same check:
    each query's reference context includes the earlier drafts of its own
    dispatch."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case()
    ref, kpr, vpr = _ragged_reference(q, kp, vp, pt, start, lens, kn, vn)
    out, kp2, vp2 = ragged_paged_attention(
        q, kp, vp, pt, start, lens, k_new=kn, v_new=vn, interpret=True
    )
    _assert_real_rows_close(out, ref, lens)
    assert (np.asarray(kp2) == np.asarray(kpr)).all()
    assert (np.asarray(vp2) == np.asarray(vpr)).all()

    # Page-boundary straddle: rows whose W tokens span two pages (the
    # merge must select per-token target pages, and the tail clamp must
    # re-apply the LAST page's merge on revisits).
    start2 = jnp.asarray([14, 30, 46], jnp.int32)
    lens2 = jnp.asarray([5, 4, 2], jnp.int32)
    ref2, kpr2, vpr2 = _ragged_reference(
        q, kp, vp, pt, start2, lens2, kn, vn)
    out2, kp3, vp3 = ragged_paged_attention(
        q, kp, vp, pt, start2, lens2, k_new=kn, v_new=vn, interpret=True
    )
    _assert_real_rows_close(out2, ref2, lens2)
    assert (np.asarray(kp3) == np.asarray(kpr2)).all()
    assert (np.asarray(vp3) == np.asarray(vpr2)).all()


def test_ragged_paged_attention_int8_bitwise():
    """int8 pools: the in-kernel quantized write of all W drafts must be
    BITWISE the host-side common.quantize_kv (values and per-(token,
    kv-head) scales) — the property that keeps speculative acceptance
    numerics identical to sequential decode under kv_quant — and the
    attention must match the dequantized-pool reference."""
    from orion_tpu.infer.kv_cache import SCALE_LANES, quantize_kv
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kf, vf, kn, vn, pt, start, lens = _ragged_case(key=11)
    num_pages, K, psz, H = kf.shape
    kq, ks = quantize_kv(kf.transpose(0, 2, 1, 3))
    vq, vs = quantize_kv(vf.transpose(0, 2, 1, 3))
    kq, vq = kq.transpose(0, 2, 1, 3), vq.transpose(0, 2, 1, 3)
    k_sc = jnp.zeros((num_pages, K, SCALE_LANES), jnp.float32
                     ).at[:, :, :psz].set(ks.transpose(0, 2, 1))
    v_sc = jnp.zeros((num_pages, K, SCALE_LANES), jnp.float32
                     ).at[:, :, :psz].set(vs.transpose(0, 2, 1))

    out, kp2, vp2, ks2, vs2 = ragged_paged_attention(
        q, kq, vq, pt, start, lens, k_new=kn, v_new=vn,
        k_scale=k_sc, v_scale=v_sc, interpret=True,
    )
    knq, kns = quantize_kv(kn)            # [B,W,K,H] i8, [B,W,K]
    vnq, vns = quantize_kv(vn)
    B = q.shape[0]
    written = set()
    for b in range(B):
        for j in range(int(lens[b])):
            p = int(start[b]) + j
            r, o = int(pt[b, p // psz]), p % psz
            written.add((r, o))
            assert (np.asarray(kp2[r, :, o]) == np.asarray(knq[b, j])).all()
            assert (np.asarray(vp2[r, :, o]) == np.asarray(vnq[b, j])).all()
            assert (np.asarray(ks2[r, :, o]) == np.asarray(kns[b, j])).all()
            assert (np.asarray(vs2[r, :, o]) == np.asarray(vns[b, j])).all()
    # Every unwritten pool/scale position is untouched.
    kp2n, kqn = np.asarray(kp2), np.asarray(kq)
    ks2n, kscn = np.asarray(ks2), np.asarray(k_sc)
    for r in range(num_pages):
        for o in range(psz):
            if (r, o) not in written:
                assert (kp2n[r, :, o] == kqn[r, :, o]).all()
                assert (ks2n[r, :, o] == kscn[r, :, o]).all()

    # Attention vs the explicitly dequantized reference.
    kd = kq.astype(jnp.float32) * k_sc[:, :, :psz][..., None]
    vd = vq.astype(jnp.float32) * v_sc[:, :, :psz][..., None]
    ref, _, _ = _ragged_reference(
        q, kd, vd, pt, start, lens,
        knq.astype(jnp.float32) * kns[..., None],
        vnq.astype(jnp.float32) * vns[..., None],
    )
    _assert_real_rows_close(out, ref, lens)


@pytest.mark.parametrize("window", [5, 20, 1000])
def test_ragged_paged_attention_sliding_window(window):
    """Per-query sliding windows over the W new positions: pages behind
    the EARLIEST query's window skip (clamped DMAs); later queries'
    tighter windows ride the mask."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case(key=7)
    ref, _, _ = _ragged_reference(
        q, kp, vp, pt, start, lens, kn, vn, window=window)
    out, _, _ = ragged_paged_attention(
        q, kp, vp, pt, start, lens, k_new=kn, v_new=vn, window=window,
        interpret=True,
    )
    _assert_real_rows_close(out, ref, lens)


def test_ragged_paged_attention_softcap():
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case(key=5)
    q = q * 4                    # push logits into the tanh's curved region
    ref, _, _ = _ragged_reference(
        q, kp, vp, pt, start, lens, kn, vn, softcap=20.0)
    out, _, _ = ragged_paged_attention(
        q, kp, vp, pt, start, lens, k_new=kn, v_new=vn,
        logit_softcap=20.0, interpret=True,
    )
    _assert_real_rows_close(out, ref, lens)


def test_ragged_w1_matches_paged_kernel_bitwise():
    """W=1 degenerates to the single-query fused-write kernel BITWISE
    (output and written pools): the ragged kernel really is the same
    kernel generalized, so spec-on pallas serving reproduces the W=1
    pallas decode stream exactly."""
    from orion_tpu.ops.pallas.paged_attention import paged_attention
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, _ = _ragged_case()
    l1 = jnp.ones(q.shape[0], jnp.int32)
    oA, kpA, vpA = ragged_paged_attention(
        q[:, :1], kp, vp, pt, start, l1,
        k_new=kn[:, :1], v_new=vn[:, :1], interpret=True,
    )
    oB, kpB, vpB = paged_attention(
        q[:, 0], kp, vp, pt, start, k_new=kn[:, 0], v_new=vn[:, 0],
        interpret=True,
    )
    assert (np.asarray(oA[:, 0]) == np.asarray(oB)).all()
    assert (np.asarray(kpA) == np.asarray(kpB)).all()
    assert (np.asarray(vpA) == np.asarray(vpB)).all()


def test_ragged_paged_attention_layer_base():
    """Traced layer_base over a flat 2-layer pool (the layer-scan calling
    convention): reads and fused writes both land in layer 1's rows."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case()
    num_pages = kp.shape[0]
    kp2 = jnp.concatenate([kp, kp * 0.5], axis=0)
    vp2 = jnp.concatenate([vp, vp * 0.5], axis=0)
    ref, kpr, vpr = _ragged_reference(
        q, kp * 0.5, vp * 0.5, pt, start, lens, kn, vn)
    out, kp3, vp3 = jax.jit(
        lambda q, kp, vp, kn, vn: ragged_paged_attention(
            q, kp, vp, pt, start, lens,
            layer_base=jnp.int32(num_pages), k_new=kn, v_new=vn,
            interpret=True,
        )
    )(q, kp2, vp2, kn, vn)
    _assert_real_rows_close(out, ref, lens)
    # Layer 0's rows untouched; layer 1's equal the reference scatter.
    assert (np.asarray(kp3[:num_pages]) == np.asarray(kp)).all()
    assert (np.asarray(kp3[num_pages:]) == np.asarray(kpr)).all()
    assert (np.asarray(vp3[num_pages:]) == np.asarray(vpr)).all()


def test_ragged_verify_fit_check():
    """The VMEM fit estimate rejects hopeless verify widths with an error
    naming the config knob, and passes the serving-scale shapes the
    kernel is built for."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        check_verify_fit,
        verify_vmem_bytes,
    )

    shape = dict(n_heads=32, n_kv_heads=8, head_dim=128, page_size=64)
    check_verify_fit(7, kv_quant=None, dtype_itemsize=2, **shape)
    check_verify_fit(7, kv_quant="int8", **shape)
    with pytest.raises(ValueError, match="speculate_tokens"):
        check_verify_fit(512, kv_quant=None, dtype_itemsize=2, **shape)
    # The estimate grows with W (the q/out/new-token blocks scale).
    small = verify_vmem_bytes(
        2, kv_itemsize=2, quant=False, **shape)
    big = verify_vmem_bytes(
        64, kv_itemsize=2, quant=False, **shape)
    assert big > small


def test_ragged_paged_attention_rejects_degenerate_window():
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q = jnp.zeros((1, 2, 4, 64))
    pool = jnp.zeros((4, 2, 16, 64))
    with pytest.raises(ValueError, match="window"):
        ragged_paged_attention(
            q, pool, pool, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros(1, jnp.int32), jnp.ones(1, jnp.int32),
            window=0, interpret=True,
        )


# -- token-tree ancestor masks (tree speculation, ISSUE 11) ------------------


def _chain_tree_arrays(B, W):
    """Chain-shaped [B, W] depth / packed-ancestor-word arrays — the
    degenerate tree whose mask must be bitwise the positional mask."""
    steps = np.arange(W, dtype=np.int64)
    depths = np.tile(steps.astype(np.int32), (B, 1))
    words = np.tile(
        ((np.int64(1) << (steps + 1)) - 1).astype(np.int32), (B, 1)
    )
    return jnp.asarray(depths), jnp.asarray(words)


def _tree_arrays(B, W, parents):
    """[B, W] depth/word arrays for one tree shape shared by all rows.
    ``parents`` is the parent COLUMN per node column 1..n (DraftTree
    layout); columns past the tree stay chain-shaped padding."""
    from orion_tpu.infer.spec_decode import DraftTree

    t = DraftTree(tokens=[0] * len(parents), parents=list(parents))
    depths, words = _chain_tree_arrays(B, W)
    n = len(parents) + 1
    depths = depths.at[:, :n].set(jnp.asarray(t.depths(), jnp.int32))
    words = words.at[:, :n].set(jnp.asarray(t.mask_words(), jnp.int32))
    return depths, words


def _tree_reference(q, k_pool, v_pool, page_table, start, lens,
                    k_new, v_new, depths, words, window=None):
    """The verify body's xla semantics under an ancestor mask: writes
    stay slot-sequential (identical to _ragged_reference's scatter), the
    committed context is visible to every query, and among the W new
    slots query c sees slot i iff bit i of its word is set (or i == c);
    sliding windows measure DEPTH distance among the new slots."""
    from orion_tpu.ops.attention import attention_xla

    B, W, N, H = q.shape
    K, psz = k_pool.shape[1], k_pool.shape[2]
    P = page_table.shape[1]
    npg = k_pool.shape[0]
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    wpos = start[:, None] + steps                          # write slots
    valid = steps < lens[:, None]
    kp = jnp.concatenate(
        [k_pool, jnp.zeros((1,) + k_pool.shape[1:], k_pool.dtype)])
    vp = jnp.concatenate(
        [v_pool, jnp.zeros((1,) + v_pool.shape[1:], v_pool.dtype)])
    rows = jnp.where(
        valid, page_table[jnp.arange(B)[:, None], wpos // psz], npg
    )
    off = wpos % psz
    kp = kp.at[rows, :, off].set(k_new)[:npg]
    vp = vp.at[rows, :, off].set(v_new)[:npg]
    k_ctx = kp[page_table].transpose(0, 1, 3, 2, 4).reshape(B, P * psz, K, H)
    v_ctx = vp[page_table].transpose(0, 1, 3, 2, 4).reshape(B, P * psz, K, H)
    kv = jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
    slot = kv - start[:, None, None]                       # [B, 1, P*psz]
    in_new = (slot >= 0) & (slot < W)
    slot_c = jnp.clip(slot, 0, W - 1)
    anc = ((words[:, :, None] >> steps[None, :, :]) & 1).astype(bool)
    anc = anc | jnp.eye(W, dtype=bool)[None]
    vis = jnp.take_along_axis(
        anc, jnp.broadcast_to(slot_c, (B, W, P * psz)), axis=2
    )
    mask = jnp.where(in_new, vis, kv < start[:, None, None])
    if window is not None:
        sdep = jnp.take_along_axis(
            jnp.broadcast_to(depths[:, None, :], (B, 1, W)), slot_c, axis=2
        )
        qdep = depths[:, :, None]
        mask &= jnp.where(
            in_new, sdep >= qdep - window + 1,
            kv >= start[:, None, None] + qdep - window + 1,
        )
    out = attention_xla(q, k_ctx, v_ctx, causal=False, mask=mask)
    return out, kp, vp


def test_ragged_tree_chain_degenerate_bitwise():
    """Chain-shaped tree words/depths produce BITWISE the plain kernel's
    outputs and written pools — the degenerate tree IS today's W-query
    verify (tree machinery adds ops, not numerics)."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case()
    B, W = q.shape[0], q.shape[1]
    depths, words = _chain_tree_arrays(B, W)
    for win in (None, 20):
        plain = ragged_paged_attention(
            q, kp, vp, pt, start, lens, k_new=kn, v_new=vn, window=win,
            interpret=True,
        )
        tree = ragged_paged_attention(
            q, kp, vp, pt, start, lens, k_new=kn, v_new=vn, window=win,
            tree_mask=words, depths=depths, interpret=True,
        )
        for a, b in zip(plain, tree):
            assert (np.asarray(a) == np.asarray(b)).all(), win


def test_ragged_tree_branchy_matches_reference():
    """A branchy ancestor mask (two sibling branches off the root, one
    nested branch) against the scatter + ancestor-masked-gather
    reference: sibling slots must NOT see each other, nested nodes see
    exactly their path, and the fused write stays slot-sequential."""
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q, kp, vp, kn, vn, pt, start, lens = _ragged_case(key=9)
    B, W = q.shape[0], q.shape[1]
    # Columns: 1<-0, 2<-1 (primary chain), 3<-0 (sibling), 4<-3 (nested).
    depths, words = _tree_arrays(B, W, parents=[0, 1, 0, 3])
    lens = jnp.asarray([W, 1, 3], jnp.int32)
    ref, kpr, vpr = _tree_reference(
        q, kp, vp, pt, start, lens, kn, vn, depths, words)
    out, kp2, vp2 = ragged_paged_attention(
        q, kp, vp, pt, start, lens, k_new=kn, v_new=vn,
        tree_mask=words, depths=depths, interpret=True,
    )
    _assert_real_rows_close(out, ref, lens)
    assert (np.asarray(kp2) == np.asarray(kpr)).all()
    assert (np.asarray(vp2) == np.asarray(vpr)).all()

    # Sliding window over the tree: depth distance, not slot distance.
    ref_w, _, _ = _tree_reference(
        q, kp, vp, pt, start, lens, kn, vn, depths, words, window=2)
    out_w, _, _ = ragged_paged_attention(
        q, kp, vp, pt, start, lens, k_new=kn, v_new=vn,
        tree_mask=words, depths=depths, window=2, interpret=True,
    )
    _assert_real_rows_close(out_w, ref_w, lens)


def test_ragged_tree_width_limit():
    from orion_tpu.ops.pallas.ragged_paged_attention import (
        ragged_paged_attention,
    )

    q = jnp.zeros((1, 32, 4, 64))
    pool = jnp.zeros((8, 2, 16, 64))
    with pytest.raises(ValueError, match="31"):
        ragged_paged_attention(
            q, pool, pool, jnp.zeros((1, 32), jnp.int32),
            jnp.zeros(1, jnp.int32), jnp.ones(1, jnp.int32),
            tree_mask=jnp.zeros((1, 32), jnp.int32),
            depths=jnp.zeros((1, 32), jnp.int32), interpret=True,
        )
