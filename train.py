#!/usr/bin/env python
"""Training entry point (reference top-level ``train.py``, BASELINE.json:5,7).

Usage:
    python train.py --preset gpt2-125m [section.key=value ...]

Examples:
    python train.py --preset tiny train.num_steps=50          # CPU smoke
    python train.py --preset llama3-8b-dp                      # v5p-64 DDP
    python train.py --preset llama3-70b-fsdp parallel.fsdp=64  # ZeRO-3
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("--preset", default="gpt2-125m")
    parser.add_argument("--list-presets", action="store_true")
    parser.add_argument("--print-config", action="store_true")
    parser.add_argument(
        "--max-restarts", type=int, default=None,
        help="supervisor mode: restart-and-resume after failures, up to N "
             "times (resumes from the newest intact checkpoint); default "
             "from train.max_restarts",
    )
    parser.add_argument(
        "--trace", metavar="PATH", default=None,
        help="export a Chrome trace-event JSON of per-step host phases "
             "(data/dispatch/guard/ckpt) to PATH when fit ends; sugar for "
             "train.trace=true + train.trace_path=PATH (combine with "
             "train.profile_steps for a device profile over the same "
             "window)",
    )
    parser.add_argument(
        "overrides", nargs="*", help="dotted config overrides, e.g. model.n_layers=4"
    )
    args = parser.parse_args(argv)

    from orion_tpu.config import get_config, list_presets

    if args.list_presets:
        print("\n".join(list_presets()))
        return 0

    overrides = list(args.overrides)
    if args.trace is not None:
        overrides += ["train.trace=true", f"train.trace_path={args.trace}"]
    cfg = get_config(args.preset, overrides)
    if args.print_config:
        print(cfg.to_json())
        return 0

    from orion_tpu.train import Trainer

    max_restarts = (
        args.max_restarts if args.max_restarts is not None
        else cfg.train.max_restarts
    )
    if max_restarts > 0:
        from orion_tpu.runtime.fault import run_with_restarts

        if not cfg.checkpoint.directory or not cfg.checkpoint.restore:
            parser.error(
                "--max-restarts needs checkpoint.directory set (and "
                "checkpoint.restore=true): without it every restart would "
                "silently retrain from step 0"
            )
        # Thread the supervisor context into each attempt's step log:
        # restart count in the metrics extras, the previous attempt's
        # fault reason on the resume log line.
        last_fault = {"reason": None}

        def _on_retry(attempt, exc):
            last_fault["reason"] = f"{type(exc).__name__}: {exc}"

        history = run_with_restarts(
            lambda attempt: Trainer(cfg).fit(
                restart_info=(attempt, last_fault["reason"])
            ),
            max_restarts=max_restarts,
            on_retry=_on_retry,
        )
    else:
        history = Trainer(cfg).fit()
    if history:
        last = history[-1]
        print(
            f"done: {last.step} steps, final loss {last.loss:.4f}, "
            f"mean MFU {sum(h.mfu for h in history) / len(history) * 100:.2f}%"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
