#!/usr/bin/env python
"""Generation entry point (reference ``inference/generate.py``,
BASELINE.json:11): continuous-batching inference over the paged KV cache.

Usage:
    python generate.py --preset tiny-llama --tokens "5,3,9" [--tokens "..."]
    python generate.py --preset gpt2-125m --prompt "hello" --byte-tokenizer

Prompts are token-id lists (``--tokens``, repeatable — each becomes one
request, served concurrently) or raw text under the byte tokenizer (demo
path; real deployments bring their own tokenizer). Parameters come from the
checkpoint directory if configured (checkpoint.directory=...), else random
init — which still exercises the full engine, scheduler and cache path.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("--preset", default="tiny-llama")
    parser.add_argument("--tokens", action="append", default=[],
                        help="comma-separated token ids (one per request)")
    parser.add_argument("--prompt", action="append", default=[],
                        help="text prompt, encoded with --byte-tokenizer")
    parser.add_argument("--byte-tokenizer", action="store_true",
                        help="encode --prompt as UTF-8 bytes (vocab >= 256)")
    parser.add_argument("--max-new-tokens", type=int, default=None)
    parser.add_argument("--eos-id", type=int, default=None)
    parser.add_argument("--stream", action="store_true",
                        help="print tokens incrementally as they decode")
    parser.add_argument("--temperature", type=float, default=None,
                        help="sampling temperature (0 = greedy); sugar for "
                             "inference.temperature")
    parser.add_argument("--top-k", type=int, default=None,
                        help="top-k sampling filter (0 disables)")
    parser.add_argument("--top-p", type=float, default=None,
                        help="nucleus sampling threshold in (0, 1]")
    parser.add_argument("--chunked-prefill", action="store_true",
                        help="bound decode stalls under prompt bursts: "
                             "split prompt prefill into page-aligned "
                             "chunks mixed into each decode step; sugar "
                             "for inference.chunked_prefill=true (budget "
                             "via inference.prefill_chunk_tokens=N)")
    parser.add_argument("--speculate", type=int, default=None, metavar="N",
                        help="speculative decoding: draft up to N tokens "
                             "per step by prompt-lookup (n-gram) and "
                             "verify them in one dispatch; greedy output "
                             "is byte-identical, sampled output keeps its "
                             "distribution; sugar for "
                             "inference.speculative=true + "
                             "inference.speculate_tokens=N")
    parser.add_argument("--regex", default=None, metavar="PATTERN",
                        help="grammar-constrained decoding: every request "
                             "emits only tokens the regex's FSM admits "
                             "(byte-level patterns over the byte "
                             "tokenizer); forced single-choice runs ride "
                             "the verify path as free drafts; sugar for "
                             "inference.constrained=true + a per-request "
                             "ConstraintSpec (mutually exclusive with "
                             "--json-schema)")
    parser.add_argument("--json-schema", default=None, metavar="FILE",
                        help="grammar-constrained decoding from a JSON "
                             "Schema file: the schema compiles to a "
                             "regex, then to the same token-level FSM "
                             "(mutually exclusive with --regex)")
    parser.add_argument("--spec-tree", type=int, default=None, metavar="W",
                        help="token-TREE speculation: draft up to W "
                             "distinct n-gram continuations per step and "
                             "verify the whole branch tree in one "
                             "dispatch (requires --speculate); sugar for "
                             "inference.spec_tree_width=W")
    parser.add_argument("--trace", metavar="PATH", default=None,
                        help="export a Chrome trace-event JSON of the "
                             "serve to PATH (request-lifecycle spans + "
                             "per-dispatch timing; load in Perfetto); "
                             "sugar for inference.trace=true + "
                             "inference.trace_path=PATH. With --replicas "
                             "N, PATH is the MERGED fleet timeline "
                             "(router + every replica on a shared clock) "
                             "and each replica also exports its own "
                             "trace.replica-k.json alongside")
    parser.add_argument("--replicas", type=int, default=None, metavar="N",
                        help="multi-replica serving: run N engine "
                             "replicas behind the health-checked router "
                             "(prefix-affinity placement, circuit-break "
                             "failover); sugar for router.replicas=N")
    parser.add_argument("--flight-dir", metavar="DIR", default=None,
                        help="flight-recorder postmortem dumps: on a "
                             "degradation trigger (watchdog stall, step "
                             "faults, NaN quarantine, spec auto-disable) "
                             "write the fault-adjacent span window to "
                             "DIR; sugar for inference.flight_dir=DIR "
                             "(render with tools/obs_report.py)")
    parser.add_argument(
        "overrides", nargs="*", help="dotted config overrides"
    )
    args = parser.parse_args(argv)

    import jax

    from orion_tpu.ckpt import CheckpointManager
    from orion_tpu.config import get_config
    from orion_tpu.infer import InferenceEngine
    from orion_tpu.models import init_params
    from orion_tpu.runtime import initialize

    # Same contract as engine.submit's per-request validation — the CLI
    # must not smuggle out-of-range values in through config overrides.
    if args.temperature is not None and args.temperature < 0.0:
        raise SystemExit(f"--temperature must be >= 0, got {args.temperature}")
    if args.top_k is not None and args.top_k < 0:
        raise SystemExit(f"--top-k must be >= 0, got {args.top_k}")
    if args.top_p is not None and not 0.0 < args.top_p <= 1.0:
        raise SystemExit(f"--top-p must be in (0, 1], got {args.top_p}")
    overrides = list(args.overrides)
    for flag, key in ((args.temperature, "inference.temperature"),
                      (args.top_k, "inference.top_k"),
                      (args.top_p, "inference.top_p")):
        if flag is not None:
            overrides.append(f"{key}={flag}")
    if args.chunked_prefill:
        overrides.append("inference.chunked_prefill=true")
    if args.speculate is not None:
        if args.speculate < 1:
            raise SystemExit(f"--speculate must be >= 1, got {args.speculate}")
        overrides.append("inference.speculative=true")
        overrides.append(f"inference.speculate_tokens={args.speculate}")
    if args.spec_tree is not None:
        if args.speculate is None:
            raise SystemExit("--spec-tree requires --speculate N")
        if args.spec_tree < 1:
            raise SystemExit(
                f"--spec-tree must be >= 1, got {args.spec_tree}"
            )
        overrides.append(f"inference.spec_tree_width={args.spec_tree}")
    if args.trace is not None:
        overrides.append("inference.trace=true")
        overrides.append(f"inference.trace_path={args.trace}")
    if args.flight_dir is not None:
        overrides.append(f"inference.flight_dir={args.flight_dir}")
    if args.replicas is not None:
        if args.replicas < 1:
            raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
        overrides.append(f"router.replicas={args.replicas}")
    constraint = None
    if args.regex is not None and args.json_schema is not None:
        raise SystemExit(
            "--regex and --json-schema are mutually exclusive (one "
            "grammar per request)"
        )
    if args.regex is not None or args.json_schema is not None:
        from orion_tpu.constrain import ConstraintError, ConstraintSpec, \
            compile_regex

        try:
            if args.regex is not None:
                constraint = ConstraintSpec(regex=args.regex)
            else:
                try:
                    with open(args.json_schema, encoding="utf-8") as f:
                        schema_text = f.read()
                except OSError as e:
                    raise SystemExit(
                        f"--json-schema {args.json_schema}: {e}"
                    )
                constraint = ConstraintSpec(json_schema=schema_text)
            # Surface malformed patterns/schemas as CLI errors, before
            # the engine builds (the engine would raise the same
            # ConstraintError at submit). pattern() parses the schema
            # frontend; compile_regex parses the regex itself.
            compile_regex(constraint.pattern())
        except ConstraintError as e:
            raise SystemExit(f"invalid constraint: {e}")
        overrides.append("inference.constrained=true")
    cfg = get_config(args.preset, overrides)
    initialize(cfg.runtime)

    prompts: list[list[int]] = []
    for spec in args.tokens:
        prompts.append([int(t) for t in spec.split(",")])
    for text in args.prompt:
        if not args.byte_tokenizer:
            raise SystemExit("--prompt requires --byte-tokenizer")
        if cfg.model.vocab_size < 256:
            raise SystemExit("byte tokenizer needs vocab_size >= 256")
        prompts.append(list(text.encode("utf-8")))
    if not prompts:
        prompts = [[1, 2, 3, 4]]

    params = init_params(cfg.model, jax.random.key(cfg.train.seed))
    if cfg.checkpoint.directory:
        # Trainer checkpoints hold the full train state; restore through the
        # SHARDED abstract state (NamedShardings attached), so a 70B-class
        # checkpoint reads directly into its mesh layout instead of
        # materializing host-side (a shapes-only eval_shape restore would
        # host-OOM at the sizes this CLI advertises).
        from orion_tpu.train.trainer import abstract_train_state

        restored = CheckpointManager(
            cfg.checkpoint.directory, cfg.checkpoint
        ).restore_latest(abstract_train_state(cfg))
        if restored is not None:
            params = restored[0]["params"]
            print(f"restored checkpoint step {restored[1]}")
            # Drop the rest of the train state (optimizer moments are 2x
            # the params) before the engine possibly quantizes.
            del restored

    from orion_tpu.runtime.fault import PreemptionHandler

    if cfg.router.replicas > 1:
        # Multi-replica serving (README "Scale-out serving"): the router
        # mirrors the engine's scheduler face — submit_request/step/
        # has_work/drain/close — so the loop below drives either.
        from orion_tpu.infer import Router

        engine = Router(cfg, params, eos_id=args.eos_id)
    else:
        engine = InferenceEngine(cfg, params, eos_id=args.eos_id)
    # The engine owns (a possibly int8-quantized copy of) the params from
    # here; keeping this reference alive would pin the full-precision
    # masters in device memory for the whole serving loop.
    del params
    # Graceful shutdown (README "Robustness"): SIGTERM only flips a flag;
    # at the next step boundary the engine stops admission, sheds the wait
    # queue with typed outcomes, FINISHES every live request — donating
    # their pages to the prefix cache exactly as normal completion does —
    # and this process exits 0 instead of dying mid-dispatch.
    with PreemptionHandler() as handler:
        reqs = [
            engine.submit_request(
                p, args.max_new_tokens, constraint=constraint
            )
            for p in prompts
        ]
        emitted = [0] * len(reqs)
        while engine.has_work():
            if handler.preempted:
                print("SIGTERM: draining (admission stopped, live "
                      "requests finishing)", flush=True)
                engine.drain()
                break
            engine.step()
            if args.stream:
                for req, n in zip(reqs, emitted):
                    if len(req.generated) > n:
                        print(f"request {req.rid} += {req.generated[n:]}",
                              flush=True)
                # High-water mark, never reset: a router failover swaps
                # the attempt and generated shrinks while the survivor
                # regenerates — already-printed tokens must not reprint.
                emitted = [
                    max(n, len(r.generated))
                    for n, r in zip(emitted, reqs)
                ]
    engine.close()
    if args.trace:
        # Re-export explicitly so the success message reflects THIS run
        # (a stale file from a previous serve must not mask a failure).
        # On a Router this is the MERGED fleet timeline: router + every
        # replica ring on a shared clock (per-replica namespaced traces
        # were written by each live replica's close() above).
        try:
            n = engine.export_trace(args.trace)
            fleet = " (merged fleet timeline)" if (
                cfg.router.replicas > 1
            ) else ""
            print(f"trace written to {args.trace}{fleet}: {n} events "
                  f"(open in Perfetto, or run "
                  f"tools/obs_report.py {args.trace})")
        except OSError as e:
            print(f"trace export to {args.trace} failed: {e}",
                  file=sys.stderr)
    for i, (prompt, req) in enumerate(zip(prompts, reqs)):
        out = req.generated
        tag = "" if req.outcome == "completed" else f" [{req.outcome}]"
        print(f"request {i}: prompt={prompt} -> generated={out}{tag}")
        if args.byte_tokenizer:
            print(f"  text: {bytes(t % 256 for t in out).decode('utf-8', 'replace')!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
